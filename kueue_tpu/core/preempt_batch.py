"""Host glue for the batched preemption kernel (ops/preempt_kernel.py).

Lowers every preempt-mode head of a cycle into local-subtree problems
and resolves ALL victim searches in one device dispatch. The host keeps
the cheap, static parts of ``core/preemption.py`` — candidate discovery
under the withinClusterQueue/reclaimWithinCohort policies, the
eviction/priority/timestamp candidate ordering, and the classic
strategy ladder (preemption.go:127-191) — and ships the expensive part
(the per-candidate simulate/undo fit evaluations) to the TPU.

Exactness notes:

- every head's search runs against the cycle-start snapshot (matching
  nomination semantics), so heads are independent and batch cleanly;
- the cell universe per head is just the head's own usage cells: the
  fit check reads only those cells, the in-loop borrowing check reads
  only frs_need_preemption cells (a subset), and quota bubbling is
  per-cell independent — so candidate usage outside the head's cells
  cannot influence any decision;
- heads the dense form can't express (fair sharing, candidate counts
  beyond the padding cap) fall back to the host Preemptor, which stays
  the decision authority for them.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu.models import Workload
from kueue_tpu.core.flavor_assigner import AssignmentResult
from kueue_tpu.core.scheduler import PreemptionTarget
from kueue_tpu.core.snapshot import Snapshot, WorkloadSnapshot
from kueue_tpu.core.solver import _bucket

# padding caps; above these a head falls back to the host path
MAX_CANDIDATES = 512
MAX_CELLS = 16


@dataclass
class _Attempt:
    entry_idx: int
    candidates: List[WorkloadSnapshot]
    allow_borrowing: bool
    threshold: Optional[int]


@dataclass
class LoweredPreemption:
    attempts: List[_Attempt] = field(default_factory=list)
    # entry index -> list of its attempt row ids (ladder order)
    rows_of: Dict[int, List[int]] = field(default_factory=dict)
    fallback: List[int] = field(default_factory=list)
    arrays: Optional[dict] = None
    depth: int = 0
    n_cand: int = 0


class _SubtreeIndex:
    """Local row numbering + paths for one root cohort's subtree."""

    __slots__ = ("rows", "local", "paths")

    def __init__(self, rows: np.ndarray, parent: np.ndarray, max_depth: int):
        self.rows = rows  # global row ids, sorted
        self.local = {int(r): i for i, r in enumerate(rows)}
        d1 = max_depth + 1
        self.paths = np.full((len(rows), d1), -1, dtype=np.int32)
        for i, r in enumerate(rows):
            cur, d = int(r), 0
            while cur >= 0 and d < d1:
                self.paths[i, d] = self.local[cur]
                cur = int(parent[cur])
                d += 1


def _head_candidates(snapshot: Snapshot, item, preemptor):
    """Shared per-head discovery: build the preemption context and the
    sorted candidate list (preemption.go:111-115), used by both the
    classic and the fair lowerings."""
    from kueue_tpu.core.preemption import _Ctx

    wl, cq_name, assignment = item
    ctx = _Ctx(
        preemptor=wl,
        cq_name=cq_name,
        cq_row=snapshot.row(cq_name),
        snapshot=snapshot,
        frs_need_preemption=preemptor._frs_need_preemption(assignment),
        usage_vec=snapshot.vector_of(assignment.usage),
    )
    candidates = preemptor._find_candidates(ctx)
    candidates.sort(key=preemptor._candidate_key(ctx))
    return ctx, candidates


def lower_preemption(
    snapshot: Snapshot,
    items: Sequence[Tuple[Workload, str, AssignmentResult]],
    preemptor,
) -> LoweredPreemption:
    """items: (workload, cq_name, PREEMPT-mode assignment) per head.
    Classic strategy ladder only — batched_get_targets routes
    fair-sharing heads to lower_fair_preemption before reaching here."""
    from kueue_tpu.ops.assign_kernel import build_roots

    out = LoweredPreemption()
    parent = snapshot.flat.parent
    roots = build_roots(parent)
    max_depth = snapshot.flat.max_depth
    subtrees: Dict[int, _SubtreeIndex] = {}

    per_attempt_meta: List[dict] = []
    for idx, item in enumerate(items):
        wl, cq_name, assignment = item
        ctx, candidates = _head_candidates(snapshot, item, preemptor)
        out.rows_of[idx] = []
        if not candidates:
            continue  # no candidates -> no targets; nothing to dispatch
        if len(candidates) > MAX_CANDIDATES:
            out.fallback.append(idx)
            continue
        cells = [int(j) for j in np.flatnonzero(ctx.usage_vec)]
        if len(cells) > MAX_CELLS:
            out.fallback.append(idx)
            continue

        cq = snapshot.cq_models[cq_name]
        same_queue = [c for c in candidates if c.cq_name == cq_name]
        ladder: List[Tuple[List[WorkloadSnapshot], bool, Optional[int]]] = []
        if len(same_queue) == len(candidates):
            ladder.append((candidates, True, None))
        else:
            allowed, threshold = preemptor._can_borrow_within_cohort(cq, ctx)
            if allowed:
                cands = candidates
                if not preemptor._queue_under_nominal(ctx):
                    cands = [
                        c
                        for c in candidates
                        if c.cq_name == cq_name or c.priority < threshold
                    ]
                ladder.append((cands, True, threshold))
            else:
                if preemptor._queue_under_nominal(ctx):
                    ladder.append((candidates, False, None))
                ladder.append((same_queue, True, None))

        for cands, allow_borrow, thr in ladder:
            row_id = len(out.attempts)
            out.attempts.append(
                _Attempt(
                    entry_idx=idx,
                    candidates=cands,
                    allow_borrowing=allow_borrow,
                    threshold=thr,
                )
            )
            out.rows_of[idx].append(row_id)
            per_attempt_meta.append(
                {"ctx": ctx, "cells": cells, "frs": ctx.frs_need_preemption}
            )

    if not out.attempts:
        return out

    w = len(out.attempts)
    n_cand = _bucket(
        max(len(a.candidates) for a in out.attempts), minimum=8
    )
    cu = _bucket(
        max(len(m["cells"]) for m in per_attempt_meta), minimum=2
    )
    # subtree panels sized to the largest involved root cohort
    needed_roots = {
        int(roots[m["ctx"].cq_row]) for m in per_attempt_meta
    }
    for root in needed_roots:
        if root not in subtrees:
            rows = np.flatnonzero(roots == root)
            subtrees[root] = _SubtreeIndex(rows, parent, max_depth)
    s = _bucket(max(len(subtrees[r].rows) for r in needed_roots), minimum=2)
    d1 = max_depth + 1

    from kueue_tpu.ops.quota import NO_LIMIT

    usage_global = snapshot.usage()
    INT_MIN = np.iinfo(np.int64).min

    paths = np.full((w, s, d1), -1, dtype=np.int32)
    usage0 = np.zeros((w, s, cu), dtype=np.int64)
    leaf0 = np.zeros((w, s, cu), dtype=np.int64)
    nominal = np.zeros((w, s, cu), dtype=np.int64)
    subtree_q = np.zeros((w, s, cu), dtype=np.int64)
    guaranteed = np.zeros((w, s, cu), dtype=np.int64)
    borrow_lim = np.full((w, s, cu), NO_LIMIT, dtype=np.int64)
    hrow = np.zeros(w, dtype=np.int32)
    need_qty = np.zeros((w, cu), dtype=np.int64)
    need_pre = np.zeros((w, cu), dtype=bool)
    allow_borrow = np.zeros(w, dtype=bool)
    has_thr = np.zeros(w, dtype=bool)
    thr = np.full(w, INT_MIN, dtype=np.int64)
    crow = np.zeros((w, n_cand), dtype=np.int32)
    cqty = np.zeros((w, n_cand, cu), dtype=np.int64)
    cvalid = np.zeros((w, n_cand), dtype=bool)
    csame = np.zeros((w, n_cand), dtype=bool)
    cprio = np.zeros((w, n_cand), dtype=np.int64)

    for a_i, (attempt, meta) in enumerate(zip(out.attempts, per_attempt_meta)):
        ctx = meta["ctx"]
        cells = meta["cells"]
        sub = subtrees[int(roots[ctx.cq_row])]
        ns, nc = len(sub.rows), len(cells)
        ix = np.ix_(sub.rows, cells)
        paths[a_i, :ns] = sub.paths
        usage0[a_i, :ns, :nc] = usage_global[ix]
        leaf0[a_i, :ns, :nc] = snapshot.local_usage[ix]
        nominal[a_i, :ns, :nc] = snapshot.nominal[ix]
        subtree_q[a_i, :ns, :nc] = snapshot.subtree[ix]
        guaranteed[a_i, :ns, :nc] = snapshot.guaranteed[ix]
        # padded cells/rows keep the NO_LIMIT init: zero quota + free
        # borrowing is inert in every recurrence
        borrow_lim[a_i, :ns, :nc] = snapshot.borrowing_limit[ix]
        hrow[a_i] = sub.local[ctx.cq_row]
        need_qty[a_i, :nc] = ctx.usage_vec[cells]
        frs_j = {
            snapshot.fr_index[fr]
            for fr in meta["frs"]
            if fr in snapshot.fr_index
        }
        need_pre[a_i, :nc] = [j in frs_j for j in cells]
        allow_borrow[a_i] = attempt.allow_borrowing
        if attempt.threshold is not None:
            has_thr[a_i] = True
            thr[a_i] = attempt.threshold
        for v, ws in enumerate(attempt.candidates):
            crow[a_i, v] = sub.local[ws.cq_row]
            cqty[a_i, v, :nc] = ws.usage_vec[cells]
            cvalid[a_i, v] = True
            csame[a_i, v] = ws.cq_name == ctx.cq_name
            cprio[a_i, v] = ws.priority

    out.arrays = dict(
        paths=paths, usage0=usage0, leaf0=leaf0, nominal=nominal,
        subtree_q=subtree_q, guaranteed=guaranteed, borrow_lim=borrow_lim,
        hrow=hrow, need_qty=need_qty, need_pre=need_pre,
        allow_borrow=allow_borrow, has_thr=has_thr, thr=thr,
        crow=crow, cqty=cqty, cvalid=cvalid, csame=csame, cprio=cprio,
        row_valid=np.ones(w, dtype=bool),
    )
    out.depth = max_depth
    out.n_cand = n_cand
    return out


def _pad_rows(arrays: dict, w_pad: int) -> dict:
    w = arrays["row_valid"].shape[0]
    if w_pad == w:
        return arrays
    out = {}
    for k, v in arrays.items():
        pad_shape = (w_pad - w,) + v.shape[1:]
        if k == "borrow_lim":
            from kueue_tpu.ops.quota import NO_LIMIT

            pad = np.full(pad_shape, NO_LIMIT, dtype=v.dtype)
        elif k == "paths":
            pad = np.full(pad_shape, -1, dtype=v.dtype)
        else:
            pad = np.zeros(pad_shape, dtype=v.dtype)
        out[k] = np.concatenate([v, pad])
    return out


def _reason_for(ws: WorkloadSnapshot, cq_name: str, thr: Optional[int]) -> str:
    from kueue_tpu.core.preemption import (
        IN_CLUSTER_QUEUE,
        IN_COHORT_RECLAIM_WHILE_BORROWING,
        IN_COHORT_RECLAMATION,
    )

    if ws.cq_name == cq_name:
        return IN_CLUSTER_QUEUE
    if thr is not None and ws.priority < thr:
        return IN_COHORT_RECLAIM_WHILE_BORROWING
    return IN_COHORT_RECLAMATION


# ---- fair sharing (ops/fair_preempt_kernel.py) ----
MAX_FAIR_CELLS = 32
MAX_FAIR_NODES = 64


def _bubble_np(paths, local_row, cells_qty, usage, guaranteed):
    """numpy addUsage bubble on a local panel (resource_node.go:123-144)."""
    path = paths[local_row]
    delta = cells_qty.copy()
    for node in path:
        if node < 0:
            break
        old = usage[node].copy()
        usage[node] += delta
        delta = np.maximum(0, usage[node] - guaranteed[node]) - np.maximum(
            0, old - guaranteed[node]
        )
        if not delta.any():
            break
    return usage


def lower_fair_preemption(
    snapshot: Snapshot,
    items: Sequence[Tuple[Workload, str, AssignmentResult]],
    preemptor,
):
    """Lower fair-sharing heads into FairProblem panels. Returns
    (problem_arrays|None, meta) where meta carries per-head candidate
    lists and the fallback indices."""
    from kueue_tpu.core.preemption import (
        LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,
        _Ctx,
    )
    from kueue_tpu.ops.assign_kernel import build_roots

    parent = snapshot.flat.parent
    roots = build_roots(parent)
    max_depth = snapshot.flat.max_depth
    n_cq = snapshot.flat.n_cq
    subtrees: Dict[int, _SubtreeIndex] = {}

    rows_meta: List[dict] = []
    fallback: List[int] = []
    empty: List[int] = []
    for idx, item in enumerate(items):
        ctx, candidates = _head_candidates(snapshot, item, preemptor)
        if not candidates:
            empty.append(idx)
            continue
        if len(candidates) > MAX_CANDIDATES:
            fallback.append(idx)
            continue
        root = int(roots[ctx.cq_row])
        sub = subtrees.get(root)
        if sub is None:
            rows = np.flatnonzero(roots == root)
            sub = _SubtreeIndex(rows, parent, max_depth)
            subtrees[root] = sub
        if len(sub.rows) > MAX_FAIR_NODES:
            fallback.append(idx)
            continue
        # ACTIVE cell universe of the whole subtree: DRS aggregates
        # over every cell carrying quota or usage, not just head cells
        panel_rows = sub.rows
        active = (
            (snapshot.nominal[panel_rows] > 0).any(axis=0)
            | (snapshot.local_usage[panel_rows] > 0).any(axis=0)
            | (ctx.usage_vec > 0)
        )
        cells = [int(j) for j in np.flatnonzero(active)]
        if len(cells) > MAX_FAIR_CELLS:
            fallback.append(idx)
            continue
        rows_meta.append(
            {"idx": idx, "ctx": ctx, "cands": candidates, "cells": cells,
             "sub": sub}
        )

    meta = {"rows": rows_meta, "fallback": fallback, "empty": empty}
    if not rows_meta:
        return None, meta

    w = len(rows_meta)
    s = _bucket(max(len(m["sub"].rows) for m in rows_meta), minimum=2)
    cu = _bucket(max(len(m["cells"]) for m in rows_meta), minimum=2)
    v = _bucket(max(len(m["cands"]) for m in rows_meta), minimum=2)
    d1 = max_depth + 1
    res_names = sorted(
        {
            snapshot.fr_list[j].resource
            for m in rows_meta
            for j in m["cells"]
        }
    )
    r = max(len(res_names) + 1, 2)  # +1 inert bucket for padded cells
    res_id = {name: i for i, name in enumerate(res_names)}

    from kueue_tpu.ops.quota import NO_LIMIT

    usage_global = snapshot.usage()
    depth_global = snapshot.flat.depth

    arrays = dict(
        paths=np.full((w, s, d1), -1, dtype=np.int32),
        usage0=np.zeros((w, s, cu), dtype=np.int64),
        subtree_q=np.zeros((w, s, cu), dtype=np.int64),
        guaranteed=np.zeros((w, s, cu), dtype=np.int64),
        borrow_lim=np.full((w, s, cu), NO_LIMIT, dtype=np.int64),
        weight=np.full((w, s), 1000, dtype=np.int64),
        parent_loc=np.full((w, s), -1, dtype=np.int32),
        depth_s=np.zeros((w, s), dtype=np.int32),
        is_cq=np.zeros((w, s), dtype=bool),
        svalid=np.zeros((w, s), dtype=bool),
        anc_of_head=np.zeros((w, s), dtype=bool),
        hrow=np.zeros(w, dtype=np.int32),
        need_qty=np.zeros((w, cu), dtype=np.int64),
        res_of=np.full((w, cu), r - 1, dtype=np.int32),  # pad: inert bucket
        crow=np.zeros((w, v), dtype=np.int32),
        cqty=np.zeros((w, v, cu), dtype=np.int64),
        cvalid=np.zeros((w, v), dtype=bool),
        row_valid=np.ones(w, dtype=bool),
    )

    for a_i, m in enumerate(rows_meta):
        ctx, sub, cells = m["ctx"], m["sub"], m["cells"]
        ns, nc = len(sub.rows), len(cells)
        ix = np.ix_(sub.rows, cells)
        arrays["paths"][a_i, :ns] = sub.paths
        arrays["usage0"][a_i, :ns, :nc] = usage_global[ix]
        arrays["subtree_q"][a_i, :ns, :nc] = snapshot.subtree[ix]
        arrays["guaranteed"][a_i, :ns, :nc] = snapshot.guaranteed[ix]
        arrays["borrow_lim"][a_i, :ns, :nc] = snapshot.borrowing_limit[ix]
        arrays["weight"][a_i, :ns] = snapshot.weight_milli[sub.rows]
        root_depth = int(depth_global[sub.rows].min())
        for i, grow in enumerate(sub.rows):
            gp = int(parent[grow])
            arrays["parent_loc"][a_i, i] = sub.local.get(gp, -1)
            arrays["depth_s"][a_i, i] = int(depth_global[grow]) - root_depth
            arrays["is_cq"][a_i, i] = grow < n_cq
        arrays["svalid"][a_i, :ns] = True
        for anc in snapshot.path_to_root(ctx.cq_row):
            li = sub.local.get(int(anc))
            if li is not None:
                arrays["anc_of_head"][a_i, li] = True
        hrow_l = sub.local[ctx.cq_row]
        arrays["hrow"][a_i] = hrow_l
        arrays["need_qty"][a_i, :nc] = ctx.usage_vec[cells]
        for ci, j in enumerate(cells):
            arrays["res_of"][a_i, ci] = res_id[snapshot.fr_list[j].resource]
        # the head's usage is part of the simulated state
        # (preemption.go:394-395 AddUsage before DRS)
        _bubble_np(
            arrays["paths"][a_i], hrow_l, arrays["need_qty"][a_i],
            arrays["usage0"][a_i], arrays["guaranteed"][a_i],
        )
        for vi, ws in enumerate(m["cands"]):
            arrays["crow"][a_i, vi] = sub.local[ws.cq_row]
            arrays["cqty"][a_i, vi, :nc] = ws.usage_vec[cells]
            arrays["cvalid"][a_i, vi] = True

    strategy1 = (
        0
        if preemptor.fs_strategies[0] == LESS_THAN_OR_EQUAL_TO_FINAL_SHARE
        else 1
    )
    meta.update(
        arrays=arrays, s=s, cu=cu, v=v, r=r, depth=max_depth,
        strategy1=strategy1, has_second=len(preemptor.fs_strategies) > 1,
    )
    return arrays, meta


def batched_fair_get_targets(
    snapshot: Snapshot,
    items: Sequence[Tuple[Workload, str, AssignmentResult]],
    preemptor,
    mesh=None,
) -> List[List[PreemptionTarget]]:
    """Fair-sharing victim sets for every preempt-mode head in one
    device dispatch; per-head fallback to the host Preemptor where the
    dense form doesn't apply. With ``mesh`` the head rows are sharded
    along ``wl`` (each device runs a slice of the independent subtree
    simulations). Parity: tests/test_fair_preempt.py."""
    from kueue_tpu._jax import jnp
    from kueue_tpu.core.preemption import (
        IN_CLUSTER_QUEUE,
        IN_COHORT_FAIR_SHARING,
    )
    from kueue_tpu.ops.fair_preempt_kernel import (
        FairProblem,
        solve_fair_packed_jit,
        split_panel_rows,
    )

    results: List[List[PreemptionTarget]] = [[] for _ in items]
    arrays, meta = lower_fair_preemption(snapshot, items, preemptor)
    for idx in meta["fallback"]:
        wl, cq_name, assignment = items[idx]
        results[idx] = preemptor.get_targets(wl, cq_name, assignment, snapshot)
    if arrays is None:
        return results

    def solve_rows(rows_arrays, v_dim):
        """One dispatch over a row subset at candidate-panel width
        ``v_dim``; returns (targets_mask, fits) for those rows."""
        w_sub = rows_arrays["row_valid"].shape[0]
        w_pad = _bucket(w_sub, minimum=8)
        if mesh is not None:
            from kueue_tpu.parallel.sharded_solver import pad_w_multiple

            w_pad = pad_w_multiple(w_pad, mesh.shape["wl"])
        rows_arrays = _pad_rows(rows_arrays, w_pad)
        problem = FairProblem(
            **{k: jnp.asarray(x) for k, x in rows_arrays.items()}
        )
        if mesh is not None:
            from kueue_tpu.parallel.sharded_solver import place_fair_problem

            problem = place_fair_problem(mesh, problem)
        flat = np.asarray(
            solve_fair_packed_jit(
                problem,
                depth=meta["depth"],
                n_cand=v_dim,
                n_local=meta["s"],
                n_res=meta["r"],
                strategy1=meta["strategy1"],
                has_second=meta["has_second"],
            )
        )  # one fetch per tier
        return (
            flat[: w_pad * v_dim].reshape(w_pad, v_dim),
            flat[w_pad * v_dim :].astype(bool),
        )

    # two-tier cost-ordered candidate panels (split_panel_rows): heads
    # whose whole pool fits the bucketed-median panel solve at the
    # narrow width (the while_loop trip count scales with V); only
    # overflowing heads pay the full-width panel. Exact by membership —
    # a head never sees a truncated view of its OWN pool. Sharded runs
    # keep the single full-width dispatch (one collective).
    counts = [len(m["cands"]) for m in meta["rows"]]
    if mesh is None:
        v_narrow, narrow_rows, wide_rows = split_panel_rows(
            counts, meta["v"], _bucket
        )
    else:
        v_narrow, narrow_rows, wide_rows = meta["v"], list(
            range(len(counts))
        ), []

    targets_of = {}
    fits_of = {}
    for rows, v_dim in ((narrow_rows, v_narrow), (wide_rows, meta["v"])):
        if not rows:
            continue
        sub = {
            k: (
                x[rows][:, :v_dim]
                if k in ("crow", "cvalid")
                else x[rows][:, :v_dim, :]
                if k == "cqty"
                else x[rows]
            )
            for k, x in arrays.items()
        }
        tmask, fits = solve_rows(sub, v_dim)
        for out_i, a_i in enumerate(rows):
            targets_of[a_i] = tmask[out_i]
            fits_of[a_i] = bool(fits[out_i])

    for a_i, m in enumerate(meta["rows"]):
        if not fits_of.get(a_i, False):
            continue
        idx = m["idx"]
        cq_name = items[idx][1]
        tmask = targets_of[a_i]
        results[idx] = [
            PreemptionTarget(
                workload=ws,
                reason=(
                    IN_CLUSTER_QUEUE
                    if ws.cq_name == cq_name
                    else IN_COHORT_FAIR_SHARING
                ),
            )
            for vi, ws in enumerate(m["cands"])
            if vi < len(tmask) and tmask[vi]
        ]
    return results


def batched_get_targets(
    snapshot: Snapshot,
    items: Sequence[Tuple[Workload, str, AssignmentResult]],
    preemptor,
) -> List[List[PreemptionTarget]]:
    """Victim sets for every preempt-mode head, one device dispatch.
    Falls back to the host Preemptor per head where the dense form
    doesn't apply. Decision parity with preemptor.get_targets is
    asserted in tests/test_preempt_batch.py."""
    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.preempt_kernel import (
        PreemptProblem,
        solve_preempt_packed_jit,
    )

    if preemptor.enable_fair_sharing:
        return batched_fair_get_targets(snapshot, items, preemptor)

    results: List[List[PreemptionTarget]] = [[] for _ in items]
    lowered = lower_preemption(snapshot, items, preemptor)
    for idx in lowered.fallback:
        wl, cq_name, assignment = items[idx]
        results[idx] = preemptor.get_targets(wl, cq_name, assignment, snapshot)
    if not lowered.attempts:
        return results

    arrays = lowered.arrays
    w = arrays["row_valid"].shape[0]
    w_pad = _bucket(w, minimum=8)
    arrays = _pad_rows(arrays, w_pad)
    problem = PreemptProblem(**{k: jnp.asarray(v) for k, v in arrays.items()})
    flat = np.asarray(
        solve_preempt_packed_jit(
            problem, depth=lowered.depth, n_cand=lowered.n_cand
        )
    )  # one fetch
    targets_mask = flat[: w_pad * lowered.n_cand].reshape(w_pad, lowered.n_cand)
    fits = flat[w_pad * lowered.n_cand :].astype(bool)

    for idx, rows in lowered.rows_of.items():
        for row_id in rows:
            if not fits[row_id]:
                continue
            attempt = lowered.attempts[row_id]
            cq_name = items[idx][1]
            results[idx] = [
                PreemptionTarget(
                    workload=ws,
                    reason=_reason_for(ws, cq_name, attempt.threshold),
                )
                for v, ws in enumerate(attempt.candidates)
                if targets_mask[row_id, v]
            ]
            break  # first fitting ladder attempt wins
    return results
