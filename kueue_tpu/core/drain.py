"""Bulk admission drain — host glue for ops/drain_kernel.py.

Lowers an entire pending backlog (every queued workload, not just the
cycle heads) into dense per-CQ queue tensors, runs the multi-cycle
drain on the device in ONE dispatch + ONE fetch, and maps the decisions
back to workloads. The per-cycle semantics match the sequential
Scheduler exactly for preemption-free, fully-representable backlogs
(asserted in tests/test_drain.py); workloads the dense form can't
express are reported in ``fallback`` for the normal cycle loop.

Use cases: the 50k-pending north-star drain (bench.py), bulk import
(cli import), and capacity what-if planning.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu.models import ResourceFlavor, Workload
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.solver import (
    MultiLowered,
    _bucket,
    lower_heads_multi,
    tree_arrays,
)


@dataclass
class DrainPlan:
    queues_np: dict  # field name -> numpy array (DrainQueues layout)
    # (q, pos) -> index into lowered.heads
    head_of: Dict[Tuple[int, int], int]
    lowered: MultiLowered
    cq_order: List[str]  # queue index -> cq name
    n_segments: int
    n_steps: int
    max_cycles: int
    fallback: List[int] = field(default_factory=list)


@dataclass
class DrainOutcome:
    # (workload, cq_name, resource->flavor map, cycle index)
    admitted: List[Tuple[Workload, str, Dict[str, str], int]]
    parked: List[Tuple[Workload, str]]
    fallback: List[Tuple[Workload, str]]
    cycles: int
    # max_cycles hit before quiescence: entries the kernel never
    # processed were routed to ``fallback`` (not parked), so the cycle
    # loop — not a silent park — decides them
    truncated: bool = False
    # the truncation-routed subset of ``fallback``: entries the kernel
    # simply never reached before max_cycles (NOT structurally
    # unrepresentable, NOT stuck-frozen) — re-running the drain over
    # exactly these from the post-apply state continues where this
    # chunk stopped. The pipelined drain loop (core/pipeline.py) feeds
    # them to the next round.
    undecided: List[Tuple[Workload, str]] = field(default_factory=list)
    # final leaf usage [N, FR] as the kernel left it — the speculative
    # post-apply snapshot the pipeline launches round t+1 against
    # (None on paths that don't report it)
    final_usage: Optional[np.ndarray] = None


def _admitted_flavors(lowered, i: int, adm_k_row) -> Dict[str, str]:
    """resource -> flavor map of an admitted head.

    Single-podset heads keep the flat {resource: flavor} shape; a
    multi-podset head returns {podset name: {resource: flavor}} (the
    per-PodSetAssignment flavors of the reference Admission)."""
    npods = int(lowered.n_podsets[i])
    wl = lowered.heads[i]
    if npods <= 1:
        return dict(lowered.candidate_flavors[i][0][int(adm_k_row[0])])
    return {
        wl.pod_sets[pp].name: dict(
            lowered.candidate_flavors[i][pp][int(adm_k_row[pp])]
        )
        for pp in range(npods)
    }


def plan_drain(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 4,
    timestamp_fn=None,
    max_podsets: int = 4,
    allow_tas: bool = False,
    policy=None,  # kueue_tpu/policy AdmissionPolicy: compiles the
    #               per-entry candidate score tensor (zeros = first-fit)
    now: float = 0.0,  # policy clock (deadline boosts)
) -> DrainPlan:
    """Lower the backlog and pack it into per-CQ queue tensors.

    ``pending`` must be in per-CQ heap order (priority desc, timestamp
    asc — use QueueManager pending snapshots); relative order across
    CQs is irrelevant.
    """
    from kueue_tpu.ops.assign_kernel import build_roots

    lowered = lower_heads_multi(
        snapshot, pending, flavors, max_candidates, max_cells, max_podsets,
        timestamp_fn, any_fungibility=True, allow_tas=allow_tas,
    )
    if policy is not None and not policy.is_default:
        from kueue_tpu.policy import annotate_multi

        annotate_multi(policy, lowered, now)
    fallback = set(lowered.fallback)

    by_cq: Dict[str, List[int]] = {}
    for i, cq_name in enumerate(lowered.cq_names):
        if i in fallback:
            continue
        by_cq.setdefault(cq_name, []).append(i)

    cq_order = sorted(by_cq)
    q = max(len(cq_order), 1)
    l = max((len(v) for v in by_cq.values()), default=1)
    k, c = max_candidates, max_cells
    # P = widest podset vector among representable heads (padded
    # podsets are inert in the kernel: no cells, mode FIT)
    pdim = max(
        [1]
        + [
            int(lowered.n_podsets[i])
            for i in range(len(lowered.heads))
            if i not in fallback
        ]
    )

    cq_rows = np.full(q, -1, dtype=np.int32)
    qlen = np.zeros(q, dtype=np.int32)
    n_podsets = np.ones((q, l), dtype=np.int32)
    cells = np.full((q, l, pdim, k, c), -1, dtype=np.int32)
    qty = np.zeros((q, l, pdim, k, c), dtype=np.int64)
    valid = np.zeros((q, l, pdim, k), dtype=bool)
    # per-group candidate cursor inputs (drain_kernel.DrainQueues):
    # G = widest resource-group vector among representable heads
    g = max(
        [1]
        + [
            lowered.n_groups[i]
            for i in range(len(lowered.heads))
            if i not in fallback
        ]
    )
    gidx = np.zeros((q, l, pdim, k, g), dtype=np.int32)
    glast = np.zeros((q, l, pdim, k, g), dtype=bool)
    cgrp = np.full(cells.shape, -1, dtype=np.int8)
    # policy candidate scores (zeros = the default first-fit policy —
    # the kernels' score-argmax then IS the first-fit walk)
    score = np.zeros((q, l, pdim, k), dtype=np.int64)
    ffb = np.ones(q, dtype=bool)
    ffp = np.zeros(q, dtype=bool)
    # convergent-retry budget per queue: the max joint cursor-odometer
    # size of its entries (clamped; see drain_kernel stuck machinery)
    retry_cap = np.full(q, 2 * max_candidates + 2, dtype=np.int32)
    priority = np.zeros((q, l), dtype=np.int64)
    timestamp = np.zeros((q, l), dtype=np.int64)
    no_reclaim = np.zeros(q, dtype=bool)
    head_of: Dict[Tuple[int, int], int] = {}

    cursor_rows_of: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    for qi, cq_name in enumerate(cq_order):
        idxs = by_cq[cq_name]
        cq_rows[qi] = snapshot.row(cq_name)
        qlen[qi] = len(idxs)
        no_reclaim[qi] = bool(lowered.no_reclaim[idxs[0]])
        ffb[qi] = bool(lowered.ffb[idxs[0]])
        ffp[qi] = bool(lowered.ffp[idxs[0]])
        retry_cap[qi] = min(
            4096, max(lowered.walk_states[i] for i in idxs) + 1
        )
        n = len(idxs)
        idx_arr = np.asarray(idxs, dtype=np.int64)
        n_podsets[qi, :n] = lowered.n_podsets[idx_arr]
        cells[qi, :n] = lowered.cells[idx_arr, :pdim]
        qty[qi, :n] = lowered.qty[idx_arr, :pdim]
        valid[qi, :n] = lowered.valid[idx_arr, :pdim]
        cgrp[qi, :n] = lowered.cgrp[idx_arr, :pdim]
        if lowered.score is not None:
            score[qi, :n] = lowered.score[idx_arr, :pdim]
        priority[qi, :n] = lowered.priority[idx_arr]
        timestamp[qi, :n] = lowered.timestamp[idx_arr]
        for pos, i in enumerate(idxs):
            head_of[(qi, pos)] = i
            for pp in range(int(lowered.n_podsets[i])):
                groups = lowered.candidate_groups[i][pp]
                # group lists are shared per lowering template: memoize
                # the dense cursor rows per list identity
                rows = cursor_rows_of.get(id(groups))
                if rows is None:
                    gi_row = np.zeros((k, g), dtype=np.int32)
                    # pad group slots (heads touching fewer than G
                    # groups) must stay permanently eligible:
                    # glast=True makes the resumed start 0
                    gl_row = np.ones((k, g), dtype=bool)
                    for kk, gvec in enumerate(groups):
                        for gx, (fi, lastf) in enumerate(gvec):
                            gi_row[kk, gx] = fi
                            gl_row[kk, gx] = lastf
                    rows = (gi_row, gl_row)
                    cursor_rows_of[id(groups)] = rows
                gidx[qi, pos, pp] = rows[0]
                glast[qi, pos, pp] = rows[1]

    roots = build_roots(snapshot.flat.parent)
    seg_id = np.full(q, -1, dtype=np.int32)
    live = cq_rows >= 0
    if live.any():
        uniq, inv = np.unique(roots[cq_rows[live]], return_inverse=True)
        seg_id[live] = inv.astype(np.int32)
        n_segments = _bucket(len(uniq), minimum=8)
        n_steps = _bucket(int(np.bincount(inv).max()), minimum=8)
        # Sound cycle cap: every cycle, each root cohort with live heads
        # retires at least one entry OR advances some head's per-group
        # flavor cursor — its rank-0 valid head admits (no in-segment
        # predecessor has touched usage yet); NoFit heads park unless a
        # group's walk stored a pending cursor, in which case the
        # cursor strictly advances and can do so at most K times per
        # entry before the walk exhausts and parks. Conflict-lost heads
        # retrying per remaining candidate pair with a same-segment
        # admission that cycle. So cycles <= largest segment's entries
        # x (1 + K pending retries each).
        max_seg_events = int(
            np.bincount(inv, weights=qlen[live].astype(np.float64)).max()
        ) * (int(retry_cap.max()) + 1)
    else:
        n_segments = n_steps = 8
        max_seg_events = 0

    return DrainPlan(
        queues_np=dict(
            cq_rows=cq_rows,
            seg_id=seg_id,
            qlen=qlen,
            cells=cells,
            qty=qty,
            valid=valid,
            n_podsets=n_podsets,
            gidx=gidx,
            glast=glast,
            cgrp=cgrp,
            ffb=ffb,
            ffp=ffp,
            retry_cap=retry_cap,
            priority=priority,
            timestamp=timestamp,
            no_reclaim=no_reclaim,
            score=score,
        ),
        head_of=head_of,
        lowered=lowered,
        cq_order=cq_order,
        n_segments=n_segments,
        n_steps=n_steps,
        # the while_loop stops at quiescence; this is a backstop only —
        # bucketed because it is a static jit arg (compile reuse)
        max_cycles=_bucket(max_seg_events + 8, minimum=16),
        # the COMPLETE fallback set — outcome mapping must use this,
        # not lowered.fallback, or extra exclusions silently vanish
        fallback=sorted(fallback),
    )


@dataclass
class DrainEviction:
    """One eviction with evictor attribution (the kernel records the
    evicting queue exactly; the evicting ENTRY is the queue's next
    admission at/after the eviction cycle — exact except for the rare
    head that evicts, then loses every later fits() re-check)."""

    victim: Workload
    victim_cq: str
    cycle: int
    by_cq: Optional[str] = None
    by_workload: Optional[Workload] = None
    # Preempted condition reason (preemption.py IN_* constants)
    reason: str = "InClusterQueue"


@dataclass
class PreemptDrainOutcome(DrainOutcome):
    # (victim workload, cq_name, cycle index of the eviction)
    preempted: List[Tuple[Workload, str, int]] = field(default_factory=list)
    # same evictions, with evictor attribution (aligned order)
    evictions: List[DrainEviction] = field(default_factory=list)


def _fair_lendable(snapshot: Snapshot, paths_np: np.ndarray):
    """(depth_of, lendable, res_of_fr) for the fair-sharing drains.

    lendable depends on quota only: potentialAvailable of the PARENT,
    summed per resource (fair_sharing.go:90-104)."""
    from kueue_tpu.ops.quota_np import potential_available_all_np

    parent = snapshot.flat.parent
    depth_of = (np.sum(paths_np >= 0, axis=1) - 1).astype(np.int32)
    pot = potential_available_all_np(
        parent, snapshot.flat.level_masks(), snapshot.subtree,
        snapshot.guaranteed, snapshot.borrowing_limit,
    )
    n_res = len(snapshot.resource_names)
    lendable = np.zeros((len(parent), n_res), dtype=np.int64)
    parent_pot = pot[np.maximum(parent, 0)]
    np.add.at(lendable.T, snapshot.resource_index, parent_pot.T)
    lendable[parent < 0] = 0
    return depth_of, lendable, snapshot.resource_index.astype(np.int32)


@dataclass
class _VictimLowering:
    """Shared per-root-cohort candidate-pool lowering, consumed by the
    classic (run_drain_preempt) and fair (run_drain_fair_preempt)
    preemption drains."""

    victims_np: dict
    slot_meta: Dict[int, list]
    victim_of: Dict[Tuple[int, int], object]
    extra_fb_entries: List[Tuple[Workload, str]]
    seg_root: Dict[int, int]
    seg_queues: Dict[int, List[int]]
    seg_members: Dict[int, List[int]]
    local_ids: Dict[int, Dict[int, int]]  # s -> global row -> local id
    row_names: list
    tree: object
    paths_j: object
    v_cap: int
    s_dim: int
    cv: int
    m_dim: int


def _lower_victim_pools(
    snapshot: Snapshot,
    plan: DrainPlan,
    timestamp_fn,
    now: Optional[float],
    max_victims: int,
    max_victim_cells: int,
    max_cycles: Optional[int],
    # fn(s, members, seg_queues_s) -> bool: extra scope veto, given the
    # segment id, its member CQ rows and its queue-index list
    extra_segment_bad=None,
    policy=None,  # kueue_tpu/policy: PREMA victim-cost adjustments
) -> _VictimLowering:
    """Build the SegVictims arrays + metadata for a preemption drain
    (the shared middle of run_drain_preempt, unchanged semantics) and
    set plan.max_cycles. Mutates plan (drops ineligible queues)."""
    from kueue_tpu.models.constants import (
        BorrowWithinCohortPolicy,
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
        WorkloadConditionType,
    )
    from kueue_tpu.ops.assign_kernel import build_roots
    from kueue_tpu.ops.drain_kernel import NO_BWC_THRESHOLD as NO_THR

    q = max(len(plan.cq_order), 1)
    nl = plan.queues_np["cells"].shape[1]
    pdim, kdim, cdim = plan.queues_np["cells"].shape[2:]
    merged_cells = pdim * cdim  # the kernel's mcells width

    # ---- per-queue preemption policy flags ----
    same_enabled = np.zeros(q, dtype=bool)
    same_prio_ok = np.zeros(q, dtype=bool)
    reclaim_enabled = np.zeros(q, dtype=bool)
    only_lower = np.zeros(q, dtype=bool)
    bwc = np.zeros(q, dtype=bool)
    bwc_thr1 = np.full(q, NO_THR, dtype=np.int64)
    for qi, cq_name in enumerate(plan.cq_order):
        prem = snapshot.cq_models[cq_name].preemption
        same_enabled[qi] = prem.within_cluster_queue != PreemptionPolicy.NEVER
        same_prio_ok[qi] = (
            prem.within_cluster_queue
            == PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY
        )
        reclaim_enabled[qi] = snapshot.has_cohort(cq_name) and (
            prem.reclaim_within_cohort != ReclaimWithinCohortPolicy.NEVER
        )
        # the host rule is != Any (preemption.py _find_candidates), not
        # == LowerPriority — they differ on unknown policy values
        only_lower[qi] = (
            prem.reclaim_within_cohort != ReclaimWithinCohortPolicy.ANY
        )
        pol = prem.borrow_within_cohort
        bwc[qi] = pol.policy != BorrowWithinCohortPolicy.NEVER
        if pol.max_priority_threshold is not None:
            bwc_thr1[qi] = int(pol.max_priority_threshold) + 1
    can_search = same_enabled | reclaim_enabled

    # ---- segment membership ----
    cq_rows = plan.queues_np["cq_rows"]
    seg_id = plan.queues_np["seg_id"]
    qlen = plan.queues_np["qlen"]
    roots_all = build_roots(snapshot.flat.parent)
    n_cq = snapshot.flat.n_cq
    row_names = snapshot.flat.cq_names  # row i -> name
    queue_of_row = {int(cq_rows[qi]): qi for qi in range(len(plan.cq_order))}
    seg_root = {}
    seg_queues: Dict[int, List[int]] = {}
    for qi in range(len(plan.cq_order)):
        s = int(seg_id[qi])
        if s < 0:
            continue
        seg_root[s] = int(roots_all[int(cq_rows[qi])])
        seg_queues.setdefault(s, []).append(qi)
    seg_members: Dict[int, List[int]] = {
        s: [r for r in range(n_cq) if int(roots_all[r]) == root]
        for s, root in seg_root.items()
    }
    scoped = {
        s: any(reclaim_enabled[qi] for qi in seg_queues[s])
        for s in seg_root
    }
    # part-B (drain-admitted-entry) slots are needed whenever ANY queue
    # in the segment searches — not just under cohort reclaim: a parked
    # higher-priority head reactivated by an eviction can preempt a
    # drain-admitted lower-priority entry of its OWN ClusterQueue
    dynamic = {
        s: any(can_search[qi] for qi in seg_queues[s]) for s in seg_root
    }

    # ---- pool membership + segment scope checks ----
    tree, paths_j, _ = tree_arrays(snapshot)
    paths_np = np.asarray(paths_j)
    pool_of: Dict[int, list] = {}  # s -> [(ws, owner_row)]
    bad_segments: List[int] = []
    for s, members in seg_members.items():
        entries = []
        bad = False
        for r in members:
            name = row_names[r]
            qi = queue_of_row.get(r)
            include = scoped[s] or (qi is not None and same_enabled[qi])
            if not include:
                continue
            for ws in snapshot.workloads_in_cq(name):
                if int(np.count_nonzero(ws.usage_vec)) > max_victim_cells:
                    # an unrepresentable victim of an included CQ makes
                    # the whole segment's searches unsound
                    bad = True
                    break
                entries.append((ws, r))
            if bad:
                break
        n_b = sum(int(qlen[qi]) for qi in seg_queues[s]) if dynamic[s] else 0
        if extra_segment_bad is not None and not bad:
            bad = bool(extra_segment_bad(s, members, seg_queues[s]))
        if bad or len(entries) + n_b > max_victims:
            bad_segments.append(s)
            pool_of[s] = []
        else:
            pool_of[s] = entries

    # searching queues of bad segments fall back to the cycle loop
    drop_queues: List[int] = [
        qi
        for s in bad_segments
        for qi in seg_queues[s]
        if can_search[qi]
    ]
    for s in bad_segments:
        scoped[s] = False
        dynamic[s] = False
    dropped = set(drop_queues)

    # ---- dense pool arrays ----
    pool_totals = [
        len(pool_of.get(s, []))
        + (
            sum(int(qlen[qi]) for qi in seg_queues[s] if qi not in dropped)
            if dynamic[s]
            else 0
        )
        for s in seg_root
    ]
    v_cap = _bucket(max(pool_totals, default=1), minimum=8)
    s_dim = plan.n_segments
    cv = max(
        merged_cells,
        max(
            (
                int(np.count_nonzero(ws.usage_vec))
                for pool in pool_of.values()
                for ws, _ in pool
            ),
            default=1,
        ),
    )
    dmax = paths_np.shape[1]
    node_counts = [
        len(
            np.unique(
                paths_np[np.asarray(members, dtype=np.int64)][
                    paths_np[np.asarray(members, dtype=np.int64)] >= 0
                ]
            )
        )
        for members in seg_members.values()
    ]
    m_dim = _bucket(max(node_counts, default=1), minimum=4)

    scells = np.full((s_dim, v_cap, cv), -1, dtype=np.int32)
    sqty = np.zeros((s_dim, v_cap, cv), dtype=np.int64)
    sprio = np.zeros((s_dim, v_cap), dtype=np.int64)
    sts = np.zeros((s_dim, v_cap), dtype=np.int64)
    svalid0 = np.zeros((s_dim, v_cap), dtype=bool)
    sowner = np.full((s_dim, v_cap), -1, dtype=np.int32)
    sowner_local = np.zeros((s_dim, v_cap), dtype=np.int32)
    sslot_q = np.full((s_dim, v_cap), -1, dtype=np.int32)
    sslot_l = np.full((s_dim, v_cap), -1, dtype=np.int32)
    seg_nodes = np.full((s_dim, m_dim), -1, dtype=np.int32)
    lpaths = np.full((s_dim, m_dim, dmax), -1, dtype=np.int32)
    hlocal = np.zeros(q, dtype=np.int32)
    perm = np.tile(np.arange(v_cap, dtype=np.int32), (q, 1))
    entry_slot = np.full((q, nl), -1, dtype=np.int32)
    victim_of: Dict[Tuple[int, int], object] = {}
    slot_meta: Dict[int, list] = {}  # s -> [(evicted0, owner, prio, rt, uid, adj)]
    # PREMA-style victim-cost adjustment (kueue_tpu/policy): inserted
    # into the candidate sort key between the (evicted, other-CQ)
    # tiers and priority; zero for every victim under the default
    # policy, so the ordering is byte-identical to the unadjusted sort
    def _cost_adjust(wl) -> int:
        if policy is None or policy.is_default:
            return 0
        return int(policy.victim_cost_adjust(wl))

    if now is None:
        rts = [
            ws.quota_reserved_time
            for pool in pool_of.values()
            for ws, _ in pool
        ]
        now = (max(rts) + 1.0) if rts else 0.0

    local_ids: Dict[int, Dict[int, int]] = {}
    for s, members in seg_members.items():
        nodes = np.unique(
            paths_np[np.asarray(members, dtype=np.int64)]
        )
        nodes = nodes[nodes >= 0]
        local_id = {int(g): i for i, g in enumerate(nodes)}
        local_ids[s] = local_id
        seg_nodes[s, : len(nodes)] = nodes
        for i, gnode in enumerate(nodes):
            gp = paths_np[int(gnode)]
            for d in range(dmax):
                if gp[d] >= 0:
                    lpaths[s, i, d] = local_id[int(gp[d])]
        for qi in seg_queues[s]:
            hlocal[qi] = local_id[int(cq_rows[qi])]
        meta = []
        slot = 0
        for ws, owner in pool_of.get(s, []):
            js = np.flatnonzero(ws.usage_vec)
            scells[s, slot, : len(js)] = js
            sqty[s, slot, : len(js)] = ws.usage_vec[js]
            sprio[s, slot] = ws.priority
            tsv = (
                timestamp_fn(ws.workload)
                if timestamp_fn
                else ws.workload.creation_time
            )
            sts[s, slot] = int(tsv * 1e9)
            svalid0[s, slot] = True
            sowner[s, slot] = owner
            sowner_local[s, slot] = local_id[int(owner)]
            victim_of[(s, slot)] = ws
            meta.append(
                (
                    ws.workload.condition_true(WorkloadConditionType.EVICTED),
                    int(owner),
                    int(ws.priority),
                    float(ws.quota_reserved_time),
                    ws.workload.uid,
                    _cost_adjust(ws.workload),
                )
            )
            slot += 1
        if dynamic[s]:
            for qi in seg_queues[s]:
                if qi in dropped:
                    continue
                for pos in range(int(qlen[qi])):
                    i = plan.head_of[(qi, pos)]
                    wl = plan.lowered.heads[i]
                    sprio[s, slot] = plan.queues_np["priority"][qi, pos]
                    sts[s, slot] = plan.queues_np["timestamp"][qi, pos]
                    sowner[s, slot] = cq_rows[qi]
                    sowner_local[s, slot] = local_id[int(cq_rows[qi])]
                    sslot_q[s, slot] = qi
                    sslot_l[s, slot] = pos
                    entry_slot[qi, pos] = slot
                    meta.append(
                        (
                            False,
                            int(cq_rows[qi]),
                            int(plan.queues_np["priority"][qi, pos]),
                            float(now),
                            wl.uid,
                            _cost_adjust(wl),
                        )
                    )
                    slot += 1
        slot_meta[s] = meta
        # candidate order per queue (preemption.go:591-618): evicted
        # first, other-CQ first, lowest priority, most recently
        # reserved, uid; pad slots last
        for qi in seg_queues[s]:
            own = int(cq_rows[qi])
            keyed = sorted(
                range(len(meta)),
                key=lambda j: (
                    0 if meta[j][0] else 1,
                    0 if meta[j][1] != own else 1,
                    meta[j][5],
                    meta[j][2],
                    -meta[j][3],
                    meta[j][4],
                ),
            )
            perm[qi, : len(keyed)] = np.asarray(keyed, dtype=np.int32)
            perm[qi, len(keyed) :] = np.arange(
                len(keyed), v_cap, dtype=np.int32
            )

    # ---- drop ineligible queues to the fallback path ----
    extra_fb_entries: List[Tuple[Workload, str]] = []
    if drop_queues:
        for qi in drop_queues:
            plan.queues_np["qlen"][qi] = 0
            plan.queues_np["cq_rows"][qi] = -1
            plan.queues_np["seg_id"][qi] = -1
            for pos in range(plan.queues_np["cells"].shape[1]):
                i = plan.head_of.pop((qi, pos), None)
                if i is not None:
                    extra_fb_entries.append(
                        (plan.lowered.heads[i], plan.lowered.cq_names[i])
                    )

    # cycle cap: between evictions the preemption-free per-segment
    # progress bound applies (>=1 retire per cycle per live segment);
    # each eviction cycle retires nothing but consumes a pool slot and
    # can reactivate the segment's parked entries once
    qlen = plan.queues_np["qlen"]
    seg_id = plan.queues_np["seg_id"]
    live = seg_id >= 0
    if live.any():
        nseg = int(seg_id[live].max()) + 1
        seg_entries = np.bincount(
            seg_id[live], weights=qlen[live].astype(np.float64), minlength=nseg
        )
        seg_victims = np.zeros(nseg, dtype=np.float64)
        for s in seg_root:
            if s < nseg:
                seg_victims[s] = len(slot_meta.get(s, []))
        # each entry may additionally burn up to max_candidates cycles
        # retrying with advanced per-group pending cursors before it
        # retires (the PendingFlavors emulation), hence the (K+1) factor
        cap = (
            int(((seg_victims + 1) * seg_entries + seg_victims).max())
            * (int(plan.queues_np["retry_cap"].max()) + 1)
            + 8
        )
    else:
        cap = 16
    plan.max_cycles = _bucket(cap, minimum=16)
    if max_cycles is not None:
        plan.max_cycles = max_cycles

    victims_np = dict(
        scells=scells, sqty=sqty, sprio=sprio, sts=sts, svalid0=svalid0,
        sowner=sowner, sowner_local=sowner_local, sslot_q=sslot_q,
        sslot_l=sslot_l, seg_nodes=seg_nodes, lpaths=lpaths,
        hlocal=hlocal, perm=perm, entry_slot=entry_slot,
        same_enabled=same_enabled, same_prio_ok=same_prio_ok,
        reclaim_enabled=reclaim_enabled, only_lower=only_lower, bwc=bwc,
        bwc_thr1=bwc_thr1,
    )
    return _VictimLowering(
        victims_np=victims_np,
        slot_meta=slot_meta,
        victim_of=victim_of,
        extra_fb_entries=extra_fb_entries,
        seg_root=seg_root,
        seg_queues=seg_queues,
        seg_members=seg_members,
        local_ids=local_ids,
        row_names=row_names,
        tree=tree,
        paths_j=paths_j,
        v_cap=v_cap,
        s_dim=s_dim,
        cv=cv,
        m_dim=m_dim,
    )


def classify_drain_scope(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    tas_flavors,
    fair_sharing: bool,
):
    """Pick which drain covers a backlog — shared by the service bulk
    path (ClusterRuntime.bulk_drain) and the CLI's ``--drain`` what-if
    plan, so the plan printout routes exactly like production.

    Returns ``(kind, pending2)`` with kind one of ``"fair_preempt"``,
    ``"fair"``, ``"preempt"``, ``"tas"``, ``"plain"``. TAS heads ride
    the drain only through run_drain_tas, which has no eviction
    support: with fair sharing or any preempt-capable plain CQ in the
    backlog they are dropped from ``pending2`` (the cycle loop decides
    them) and the rest drains under the preempt/fair scopes.
    """
    from kueue_tpu.models.constants import (
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
    )

    tas_flavors = set(tas_flavors or ())

    def _on_tas_cq(cq_name: str) -> bool:
        cq = snapshot.cq_models.get(cq_name)
        return cq is not None and any(
            fq.name in tas_flavors
            for rg in cq.resource_groups
            for fq in rg.flavors
        )

    def _preempt_capable(cq_name: str) -> bool:
        cq = snapshot.cq_models.get(cq_name)
        if cq is None:
            return False
        prem = cq.preemption
        return prem.within_cluster_queue != PreemptionPolicy.NEVER or (
            snapshot.has_cohort(cq_name)
            and prem.reclaim_within_cohort != ReclaimWithinCohortPolicy.NEVER
        )

    cq_names = {c for _, c in pending}
    tas_cqs = (
        {c for c in cq_names if _on_tas_cq(c)} if tas_flavors else set()
    )
    any_preempt = any(_preempt_capable(c) for c in cq_names - tas_cqs)
    use_tas = bool(tas_cqs) and not fair_sharing and not any_preempt
    pending2 = list(pending)
    if tas_cqs and not use_tas:
        pending2 = [(w, c) for w, c in pending2 if c not in tas_cqs]
    if fair_sharing and any_preempt:
        return "fair_preempt", pending2
    if fair_sharing:
        return "fair", pending2
    if any_preempt:
        return "preempt", pending2
    if use_tas:
        return "tas", pending2
    return "plain", pending2


def run_drain_for_scope(
    kind: str,
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    tas_cache=None,
    fs_strategies=None,
    timestamp_fn=None,
    mesh=None,  # jax.sharding.Mesh: shard every drain kind's Q axis
    policy=None,  # kueue_tpu/policy AdmissionPolicy, every kind
    now: float = 0.0,
):
    """Dispatch the drain a classify_drain_scope kind names — the ONE
    place the kind→drain mapping lives, so the service bulk path and
    the CLI what-if stay identical by construction. ``mesh`` flows to
    every kind: the whole drain family runs under a ``(wl[, fr])`` mesh
    with decisions bit-for-bit the single-device kernels'
    (tests/test_mesh_drain.py)."""
    if kind == "fair_preempt":
        return run_drain_fair_preempt(
            snapshot, pending, flavors, timestamp_fn=timestamp_fn,
            fs_strategies=fs_strategies, mesh=mesh, policy=policy,
        )
    if kind == "fair":
        return run_drain(
            snapshot, pending, flavors, timestamp_fn=timestamp_fn,
            fair_sharing=True, mesh=mesh, policy=policy, now=now,
        )
    if kind == "preempt":
        return run_drain_preempt(
            snapshot, pending, flavors, timestamp_fn=timestamp_fn, mesh=mesh,
            policy=policy,
        )
    if kind == "tas":
        return run_drain_tas(
            snapshot, pending, flavors, tas_cache, timestamp_fn=timestamp_fn,
            mesh=mesh, policy=policy, now=now,
        )
    return run_drain(
        snapshot, pending, flavors, timestamp_fn=timestamp_fn, mesh=mesh,
        policy=policy, now=now,
    )


def launch_drain_for_scope(
    kind: str,
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    timestamp_fn=None,
    max_cycles: Optional[int] = None,
    mesh=None,
    resident=None,
    policy=None,  # kueue_tpu/policy AdmissionPolicy
    now: float = 0.0,
) -> Optional[DrainLaunch]:
    """Async (launch/fetch) twin of ``run_drain_for_scope`` for the
    scopes the pipelined drain loop can double-buffer. Returns None for
    scopes without a launch/fetch split yet (fair / preempt / TAS keep
    the blocking path — the pipeline falls back to serial rounds for
    them)."""
    if kind != "plain":
        return None
    return launch_drain(
        snapshot, pending, flavors, timestamp_fn=timestamp_fn,
        max_cycles=max_cycles, mesh=mesh, resident=resident,
        policy=policy, now=now,
    )


class PanelTuner:
    """Online kernel-shape search for the victim-search panel width.

    The contended drain is THROUGHPUT-bound in the strategy-ladder scan
    (BENCH_NOTES_r05.md: cost scales with ``search_width``; fusing the
    two attempts changed nothing), so the shape lever is the panel
    width itself. Candidate panels are already sorted by the
    preemption-cost key (evicted first, other-CQ first, lowest
    priority, most recently reserved — preemption.go:591-618, the
    ordering PREMA/arXiv:1909.04548 motivates), so the true victim set
    is a PREFIX of the panel in the common case and a narrow window
    finds it. Exactness is guaranteed by the escape hatch in
    ``run_drain_preempt``: a solve whose ``overflowed`` flag fired
    (some eligible list overflowed the window AND the search missed)
    is discarded and re-solved at the next wider width, ending at the
    exact ``search_width`` — decisions are bit-for-bit the fixed-width
    kernel's at every step.

    This tuner is the per-workload-mix coordinate descent of
    arXiv:2406.20037 reduced to the one live coordinate: per final
    (exact) width it walks the width ladder — an escalation widens the
    starting panel for the next call, ``shrink_after`` consecutive
    clean narrow solves try the next narrower rung. State only ever
    changes WHICH executable runs, never what it answers."""

    LADDER = (8, 16, 32, 64, 128, 256, 512)

    def __init__(self, shrink_after: int = 8):
        self.shrink_after = shrink_after
        self._narrow: Dict[int, int] = {}  # final width -> narrow width
        self._clean: Dict[int, int] = {}  # consecutive clean solves
        self.escalations = 0
        self.solves = 0

    def _default_narrow(self, final: int) -> int:
        for w in self.LADDER:
            if w * 4 >= final:
                return min(w, final)
        return final

    def widths_for(self, final: int) -> Tuple[int, ...]:
        """The width schedule for one drain: (narrow, ..., final)."""
        narrow = self._narrow.get(final)
        if narrow is None:
            narrow = self._default_narrow(final)
            self._narrow[final] = narrow
        if narrow >= final:
            return (final,)
        return (narrow, final)

    def observe(self, final: int, escalated: bool) -> None:
        self.solves += 1
        narrow = self._narrow.get(final, final)
        if escalated:
            self.escalations += 1
            self._clean[final] = 0
            # widen: next rung up (capped at final)
            self._narrow[final] = min(final, max(narrow * 2, 8))
        else:
            n = self._clean.get(final, 0) + 1
            self._clean[final] = n
            if n >= self.shrink_after and narrow > self.LADDER[0]:
                self._narrow[final] = narrow // 2
                self._clean[final] = 0


# process-wide default tuner: the production runtime and the bench
# share it so the shape converges to the live workload mix
_PANEL_TUNER = PanelTuner()

# operator override (server --panel-widths): a fixed schedule replaces
# the tuner's; None = tune online
_PANEL_WIDTHS_OVERRIDE: Optional[Tuple[int, ...]] = None


def set_default_panel_widths(widths: Optional[Sequence[int]]) -> None:
    """Pin the victim-search panel schedule process-wide (the server's
    ``--panel-widths`` knob); None restores the online PanelTuner."""
    global _PANEL_WIDTHS_OVERRIDE
    _PANEL_WIDTHS_OVERRIDE = tuple(widths) if widths is not None else None


def run_drain_preempt(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 4,
    max_victims: int = 512,
    max_victim_cells: int = 4,
    timestamp_fn=None,
    max_cycles: Optional[int] = None,
    now: Optional[float] = None,
    search_width: int = 32,
    mesh=None,  # jax.sharding.Mesh: shard the Q axis across devices
    panel_widths: Optional[Sequence[int]] = None,
    panel_tuner: Optional[PanelTuner] = None,
    policy=None,  # kueue_tpu/policy AdmissionPolicy: scored flavor
    #               choice + PREMA victim-cost adjustments
    # internal (the narrow-panel GSPMD probe): run the given
    # panel_widths under the mesh WITHOUT consulting the probe verdict
    # — the probe itself is what establishes it
    _trust_panel_widths: bool = False,
) -> PreemptDrainOutcome:
    """Multi-cycle drain WITH classic preemption — within-ClusterQueue
    and cross-CQ cohort reclamation — in one device dispatch + one
    fetch (ops/drain_kernel.solve_drain_preempt).

    Candidates are pooled per root cohort (segment): every member CQ's
    admitted workloads (part A), plus one slot per pending entry that
    becomes a live reclaim candidate once the drain admits it (part B —
    the host cycle loop sees drain-admitted workloads in its snapshot
    the same way). ``now`` is the quota-reservation instant attributed
    to in-drain admissions for candidate ordering (default: after every
    part-A reservation). ``max_victims`` caps a SEGMENT's pool;
    overflowing segments route their preempt-capable queues to
    ``fallback`` for the sequential cycle loop, as do victims with more
    than ``max_victim_cells`` distinct usage cells. ``search_width``
    bounds one head's per-cycle candidate scan; a head that fails an
    overflowing search is reported via ``fallback`` (no-decision), not
    parked. The caller applies the reported admissions and evictions in
    cycle order (a drain-admitted entry may later be evicted by a
    reclaiming CQ: it appears in BOTH lists) — this function only
    decides.

    ``panel_widths`` overrides the panel schedule (last entry = the
    trusted exact width); default is the process-wide ``PanelTuner``'s
    (narrow, search_width) schedule — the solve runs at the narrow
    cost-ordered panel and re-solves at the wide exact width ONLY when
    the kernel reports an inconclusive truncated search, so decisions
    always equal the fixed-``search_width`` kernel's (asserted in
    tests/test_drain_parity.py).
    """
    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.drain_kernel import (
        DrainQueues,
        SegVictims,
        solve_drain_preempt_packed_jit,
    )

    plan = plan_drain(
        snapshot, pending, flavors, max_candidates, max_cells, timestamp_fn,
        policy=policy, now=now or 0.0,
    )
    low = _lower_victim_pools(
        snapshot, plan, timestamp_fn, now, max_victims, max_victim_cells,
        max_cycles, policy=policy,
    )
    tree, paths_j = low.tree, low.paths_j
    victims_np = low.victims_np

    queues_np = plan.queues_np
    if mesh is not None:
        import time as _time

        from kueue_tpu.parallel import harness
        from kueue_tpu.parallel.sharded_solver import (
            pad_queue_arrays,
            pad_victim_arrays,
            place_preempt_drain_inputs,
        )

        t0p = _time.perf_counter()
        mult = mesh.shape["wl"]
        queues_np = pad_queue_arrays(queues_np, mult)
        victims_np = pad_victim_arrays(
            victims_np, queues_np["qlen"].shape[0]
        )
        tree_in, usage_in, queues, victims, paths_in = (
            place_preempt_drain_inputs(
                mesh,
                tree,
                snapshot.local_usage,
                DrainQueues(**queues_np),
                SegVictims(**victims_np),
                paths_j,
            )
        )
        harness.note_place_seconds(_time.perf_counter() - t0p)
    else:
        tree_in, paths_in = tree, paths_j
        usage_in = jnp.asarray(snapshot.local_usage)
        queues = DrainQueues(
            **{k: jnp.asarray(v) for k, v in queues_np.items()}
        )
        victims = SegVictims(
            **{k: jnp.asarray(v) for k, v in victims_np.items()}
        )
    # ---- the two-tier panel ladder (exactness escape hatch) ----
    # Solve at the narrow cost-ordered panel first; if ANY head's
    # search overflowed the window and missed (the kernel's
    # ``overflowed`` flag — the only way truncation can be inexact),
    # discard and re-solve at the next wider width, ending at the
    # exact ``search_width``. Decisions are therefore bit-for-bit the
    # fixed ``search_width`` kernel's: a clean narrow run is provably
    # identical (every search succeeded in-window or failed with its
    # whole eligible list in-window), and an escalated run IS the wide
    # run.
    tuner = panel_tuner if panel_tuner is not None else _PANEL_TUNER
    if panel_widths is None:
        panel_widths = _PANEL_WIDTHS_OVERRIDE
    if panel_widths is not None:
        widths = tuple(panel_widths)
    else:
        widths = tuner.widths_for(search_width)
    if mesh is not None and not _trust_panel_widths:
        # The GSPMD partitioner miscompiles the narrow-panel compaction
        # at small static widths (mixed s32/s64 index compare in the
        # partitioned HLO). Under a mesh each narrow rung therefore
        # runs only after a per-(mesh, width) canary PROVES the
        # partitioned solve reproduces single-device decisions
        # (parallel/harness.narrow_panels_supported, memoized);
        # unsupported rungs are clamped up the ladder, degenerating to
        # the pinned exact ``search_width`` where the miscompile is
        # real at every rung (the PR-7 fence). The exactness escape
        # hatch is unchanged either way: ``overflowed`` is replicated
        # across shards and escalation re-solves wider, so a clean
        # narrow run is provably the wide run's decisions.
        from kueue_tpu.parallel.harness import (
            mesh_safe_widths,
            note_panel_schedule,
        )

        safe = mesh_safe_widths(mesh, widths)
        note_panel_schedule(safe, fenced=safe != widths)
        widths = safe
    escalated = False
    for i, width in enumerate(widths):
        if mesh is not None:
            from kueue_tpu.parallel import harness

            harness.note_bucket(
                "preempt_kernel",
                (
                    queues_np["cells"].shape, plan.n_segments, plan.n_steps,
                    plan.max_cycles, int(width),
                ),
                mesh,
            )
        try:
            flat = np.asarray(
                solve_drain_preempt_packed_jit(
                    tree_in,
                    usage_in,
                    queues,
                    victims,
                    paths_in,
                    n_segments=plan.n_segments,
                    n_steps=plan.n_steps,
                    max_cycles=plan.max_cycles,
                    search_width=int(width),
                )
            )  # one fetch per tier; the common case stops at the first
        except Exception as exc:
            from kueue_tpu.testing import faults

            if (
                mesh is None
                or i == len(widths) - 1
                or isinstance(exc, faults.InjectedCrash)
            ):
                raise
            # The GSPMD miscompile is shape-dependent: the canary probe
            # certifies a width per MESH, but a particular problem's
            # partitioned HLO can still be rejected by the verifier at
            # a narrow width (loud compile failure, never a silent
            # wrong answer). Demote the width for this mesh — future
            # schedules clamp past it — and escalate to the next rung;
            # only the final exact width is allowed to raise.
            from kueue_tpu.parallel.harness import demote_panel_width

            demote_panel_width(mesh, int(width))
            escalated = True
            continue
        overflowed = bool(flat[-2])
        if not overflowed or i == len(widths) - 1:
            break
        escalated = True
    if panel_widths is None:
        tuner.observe(search_width, escalated)
    return _preempt_outcome(plan, low, flat, queues_np, fair=False)


def _preempt_outcome(
    plan: DrainPlan,
    low: _VictimLowering,
    flat: np.ndarray,
    queues_np: dict,
    fair: bool,
) -> PreemptDrainOutcome:
    """Unpack a PreemptDrainResult flat vector and map decisions back
    to workloads (shared by the classic and fair preemption drains;
    ``fair`` switches the Preempted-condition reason rules)."""
    lowered = plan.lowered
    s_dim, v_cap = low.s_dim, low.v_cap
    slot_meta, victim_of = low.slot_meta, low.victim_of
    seg_root, row_names = low.seg_root, low.row_names
    extra_fb_entries = low.extra_fb_entries
    victims_np = low.victims_np
    sowner = victims_np["sowner"]
    sprio = victims_np["sprio"]
    sslot_q = victims_np["sslot_q"]
    sslot_l = victims_np["sslot_l"]
    bwc = victims_np["bwc"]
    bwc_thr1 = victims_np["bwc_thr1"]
    cq_rows = plan.queues_np["cq_rows"]

    nq, nl2, npd = queues_np["cells"].shape[:3]  # incl. mesh padding
    ql, sv, qlp = nq * nl2, s_dim * v_cap, nq * nl2 * npd
    off = 0
    status = flat[off : off + ql].reshape((nq, nl2)); off += ql
    adm_k = flat[off : off + qlp].reshape((nq, nl2, npd)); off += qlp
    adm_cycle = flat[off : off + ql].reshape((nq, nl2)); off += ql
    evicted = flat[off : off + sv].reshape((s_dim, v_cap)).astype(bool); off += sv
    evict_cycle = flat[off : off + sv].reshape((s_dim, v_cap)); off += sv
    evict_by = flat[off : off + sv].reshape((s_dim, v_cap)); off += sv
    stuck_q = flat[off : off + nq].astype(bool); off += nq
    cycles = int(flat[-1])
    # truncated = the CYCLE CAP cut undecided work; queues frozen by
    # the stuck machinery are a terminal no-decision, not truncation —
    # rerunning with a larger cap cannot resolve them
    truncated = bool(
        np.any(
            (status == 0)
            & (np.arange(nl2)[None, :] < queues_np["qlen"][:, None])
            & ~stuck_q[:, None]
        )
    )

    lowered = plan.lowered
    admitted: List[Tuple[Workload, str, Dict[str, str], int]] = []
    parked: List[Tuple[Workload, str]] = []
    extra_fallback: List[Tuple[Workload, str]] = list(extra_fb_entries)
    for (qi, pos), i in plan.head_of.items():
        wl = lowered.heads[i]
        cq_name = lowered.cq_names[i]
        st = int(status[qi, pos])
        kk = int(adm_k[qi, pos, 0])
        if st == 2 and kk >= 0:
            admitted.append(
                (wl, cq_name, _admitted_flavors(lowered, i, adm_k[qi, pos]),
                 int(adm_cycle[qi, pos]))
            )
        elif st == 0:
            # still pending at max_cycles: not a decision
            extra_fallback.append((wl, cq_name))
        else:
            parked.append((wl, cq_name))
    admitted.sort(key=lambda t: t[3])
    from kueue_tpu.core.preemption import (
        IN_CLUSTER_QUEUE,
        IN_COHORT_FAIR_SHARING,
        IN_COHORT_RECLAIM_WHILE_BORROWING,
        IN_COHORT_RECLAMATION,
    )
    from kueue_tpu.ops.drain_kernel import NO_BWC_THRESHOLD

    def _evictor_entry(qi: int, cyc: int):
        """(workload, priority) of queue qi's evicting entry at cycle
        cyc: its next admission at/after cyc (a preempting head charges
        usage at the eviction cycle and admits at a later one); falls
        back to the queue's first never-admitted entry when the head
        lost every later re-check and parked."""
        best = None
        first_unadmitted = None
        for pos in range(int(plan.queues_np["qlen"][qi])):
            i = plan.head_of.get((qi, pos))
            if i is None:
                continue
            if int(status[qi, pos]) == 2:
                ac = int(adm_cycle[qi, pos])
                if ac >= cyc and (best is None or ac < best[0]):
                    best = (ac, i, pos)
            elif first_unadmitted is None:
                first_unadmitted = (i, pos)
        if best is not None:
            i, pos = best[1], best[2]
        elif first_unadmitted is not None:
            i, pos = first_unadmitted
        else:
            return None, 0
        return (
            lowered.heads[i],
            int(plan.queues_np["priority"][qi, pos]),
        )

    preempted: List[Tuple[Workload, str, int]] = []
    evictions: List[DrainEviction] = []
    for s in seg_root:
        for slot in range(len(slot_meta.get(s, []))):
            if not evicted[s, slot]:
                continue
            cyc = int(evict_cycle[s, slot])
            ws = victim_of.get((s, slot))
            if ws is not None:
                victim_wl = ws.workload
                victim_cq = row_names[int(sowner[s, slot])]
            else:
                qi, pos = int(sslot_q[s, slot]), int(sslot_l[s, slot])
                i = plan.head_of.get((qi, pos))
                if i is None:
                    continue
                victim_wl = lowered.heads[i]
                victim_cq = lowered.cq_names[i]
            preempted.append((victim_wl, victim_cq, cyc))
            qi_by = int(evict_by[s, slot])
            by_cq = by_wl = None
            reason = IN_CLUSTER_QUEUE
            if 0 <= qi_by < len(plan.cq_order):
                by_cq = plan.cq_order[qi_by]
                by_wl, by_prio = _evictor_entry(qi_by, cyc)
                if int(cq_rows[qi_by]) != int(sowner[s, slot]):
                    if fair:
                        # fair tournament victims from another CQ
                        # (preemption.py _fair_preemptions)
                        reason = IN_COHORT_FAIR_SHARING
                    else:
                        # the ladder's threshold rule
                        # (preemption.go:353-357): below min(evictor
                        # priority, maxPriorityThreshold+1) the reclaim
                        # rode borrowWithinCohort
                        thr = min(
                            by_prio, int(bwc_thr1[qi_by]), NO_BWC_THRESHOLD
                        )
                        if bwc[qi_by] and int(sprio[s, slot]) < thr:
                            reason = IN_COHORT_RECLAIM_WHILE_BORROWING
                        else:
                            reason = IN_COHORT_RECLAMATION
            evictions.append(
                DrainEviction(
                    victim=victim_wl, victim_cq=victim_cq, cycle=cyc,
                    by_cq=by_cq, by_workload=by_wl, reason=reason,
                )
            )
    order = sorted(range(len(preempted)), key=lambda ix: preempted[ix][2])
    preempted = [preempted[ix] for ix in order]
    evictions = [evictions[ix] for ix in order]
    fb = [
        (lowered.heads[i], lowered.cq_names[i]) for i in plan.fallback
    ] + extra_fallback
    return PreemptDrainOutcome(
        admitted=admitted,
        parked=parked,
        fallback=fb,
        cycles=cycles,
        truncated=truncated,
        preempted=preempted,
        evictions=evictions,
    )


def run_drain_fair_preempt(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 4,
    max_victims: int = 512,
    max_victim_cells: int = 4,
    max_fair_cells: int = 64,
    timestamp_fn=None,
    max_cycles: Optional[int] = None,
    now: Optional[float] = None,
    policy=None,  # kueue_tpu/policy AdmissionPolicy
    fs_strategies: Optional[Sequence[str]] = None,
    mesh=None,  # jax.sharding.Mesh: shard the Q axis across devices
) -> PreemptDrainOutcome:
    """Multi-cycle drain with FAIR-SHARING admission ordering AND
    fair-sharing preemption — the production fair-cohort configuration
    — in one device dispatch + one fetch
    (ops/drain_kernel.solve_drain_fair_preempt).

    With ``mesh`` the per-queue tensors (and SegVictims' per-queue
    config) shard along ``wl``; the candidate pools, fair panels and
    node-space extras stay replicated — every panel tensor is SEGMENT
    space, the tournament reduces over whole root cohorts on each
    shard, and decisions are bit-for-bit the single-device kernel's
    (tests/test_mesh_drain.py).

    The candidate pools are the classic preemption drain's (fair
    sharing shares _find_candidates and the candidate ordering —
    preemption.go:480-524, :591-618); on top of them each segment gets
    LOCAL fair panels carrying its ACTIVE cell universe (every
    flavor-resource with quota or usage anywhere in the root cohort
    plus every queued entry's candidate cells — DRS aggregates over all
    of them, fair_sharing.go:49-104). A segment whose universe exceeds
    ``max_fair_cells`` routes its preempt-capable queues to
    ``fallback``, like the host batcher's MAX_FAIR_CELLS cap
    (core/preempt_batch.py). ``fs_strategies`` defaults to the
    Preemptor's [LessThanOrEqualToFinalShare, LessThanInitialShare].
    Victim attribution reasons are InClusterQueue / InCohortFairSharing
    (preemption.py _fair_preemptions)."""
    from kueue_tpu._jax import jnp
    from kueue_tpu.features import enabled as _feature_enabled
    from kueue_tpu.core.preemption import (
        LESS_THAN_OR_EQUAL_TO_FINAL_SHARE,
        LESS_THAN_INITIAL_SHARE,
    )
    from kueue_tpu.ops.drain_kernel import (
        DrainQueues,
        FairSegPanels,
        SegVictims,
        solve_drain_fair_preempt_packed_jit,
    )

    plan = plan_drain(
        snapshot, pending, flavors, max_candidates, max_cells, timestamp_fn,
        policy=policy, now=now or 0.0,
    )
    parent_arr = snapshot.flat.parent
    n_cq = snapshot.flat.n_cq
    n_res = len(snapshot.resource_names)
    res_of_fr = snapshot.resource_index.astype(np.int32)
    universe_of: Dict[int, np.ndarray] = {}
    qlen_np = plan.queues_np["qlen"]

    def seg_universe_bad(s: int, members, seg_queues_s) -> bool:
        """Compute the segment's active cell universe; veto the segment
        (dropping its searching queues to fallback) when it exceeds the
        panel cap. ``seg_queues_s`` is the lowering's own queue list
        for this segment."""
        nodes = set()
        for r in members:
            cur = int(r)
            while cur >= 0:
                nodes.add(cur)
                cur = int(parent_arr[cur])
        rows = np.asarray(sorted(nodes), dtype=np.int64)
        active = (snapshot.nominal[rows] > 0).any(axis=0) | (
            snapshot.local_usage[rows] > 0
        ).any(axis=0)
        for qi in seg_queues_s:
            cells_q = plan.queues_np["cells"][qi, : int(qlen_np[qi])]
            cs = cells_q[cells_q >= 0]
            if cs.size:
                active[np.unique(cs)] = True
        universe_of[s] = np.flatnonzero(active).astype(np.int32)
        return len(universe_of[s]) > max_fair_cells

    low = _lower_victim_pools(
        snapshot, plan, timestamp_fn, now, max_victims, max_victim_cells,
        max_cycles, extra_segment_bad=seg_universe_bad, policy=policy,
    )
    tree, paths_j = low.tree, low.paths_j
    victims_np = low.victims_np
    s_dim, v_cap, m_dim = low.s_dim, low.v_cap, low.m_dim

    # ---- fair panels ----
    good = {
        s: u for s, u in universe_of.items() if len(u) <= max_fair_cells
    }
    cu = _bucket(max((len(u) for u in good.values()), default=1), minimum=2)
    seg_cells = np.full((s_dim, cu), -1, dtype=np.int32)
    parent_local = np.full((s_dim, m_dim), -1, dtype=np.int32)
    depth_local = np.zeros((s_dim, m_dim), dtype=np.int32)
    is_cq_local = np.zeros((s_dim, m_dim), dtype=bool)
    node_valid = np.zeros((s_dim, m_dim), dtype=bool)
    weight_local = np.full((s_dim, m_dim), 1000, dtype=np.int64)
    res_of_cell = np.full((s_dim, cu), n_res, dtype=np.int32)
    svqty_cu = np.zeros((s_dim, v_cap, cu), dtype=np.int64)

    paths_np = np.asarray(paths_j)
    depth_of, lendable, _ = _fair_lendable(snapshot, paths_np)
    victims_by_seg: Dict[int, List[Tuple[int, object]]] = {}
    for (ss, slot), ws in low.victim_of.items():
        victims_by_seg.setdefault(ss, []).append((slot, ws))
    for s, local_id in low.local_ids.items():
        u = good.get(s)
        if u is None:
            continue  # vetoed segment: panels stay inert
        seg_cells[s, : len(u)] = u
        res_of_cell[s, : len(u)] = res_of_fr[u]
        root_depth = min(int(depth_of[g]) for g in local_id)
        for gnode, li in local_id.items():
            node_valid[s, li] = True
            is_cq_local[s, li] = gnode < n_cq
            parent_local[s, li] = local_id.get(int(parent_arr[gnode]), -1)
            weight_local[s, li] = int(snapshot.weight_milli[gnode])
            depth_local[s, li] = int(depth_of[gnode]) - root_depth
        cell_pos = {int(j): ci for ci, j in enumerate(u)}
        for slot, ws in victims_by_seg.get(s, ()):
            for j in np.flatnonzero(ws.usage_vec):
                ci = cell_pos.get(int(j))
                if ci is None:  # usage cells are in the universe by
                    raise AssertionError(  # construction
                        f"victim cell {j} outside segment {s} universe"
                    )
                svqty_cu[s, slot, ci] = int(ws.usage_vec[j])

    strategies = list(
        fs_strategies
        or [LESS_THAN_OR_EQUAL_TO_FINAL_SHARE, LESS_THAN_INITIAL_SHARE]
    )
    strategy1 = (
        0 if strategies[0] == LESS_THAN_OR_EQUAL_TO_FINAL_SHARE else 1
    )

    queues_np = plan.queues_np
    fairp_np = dict(
        seg_cells=seg_cells, parent_local=parent_local,
        depth_local=depth_local, is_cq_local=is_cq_local,
        node_valid=node_valid, weight_local=weight_local,
        res_of_cell=res_of_cell, svqty_cu=svqty_cu,
    )
    if mesh is not None:
        import time as _time

        from kueue_tpu.parallel import harness
        from kueue_tpu.parallel.sharded_solver import (
            pad_queue_arrays,
            pad_victim_arrays,
            place_fair_drain_extras,
            place_fair_preempt_drain_inputs,
        )

        t0p = _time.perf_counter()
        queues_np = pad_queue_arrays(queues_np, mesh.shape["wl"])
        victims_np = pad_victim_arrays(victims_np, queues_np["qlen"].shape[0])
        tree_in, usage_in, queues, victims, fairp, paths_in = (
            place_fair_preempt_drain_inputs(
                mesh,
                tree,
                snapshot.local_usage,
                DrainQueues(**queues_np),
                SegVictims(**victims_np),
                FairSegPanels(**fairp_np),
                paths_j,
            )
        )
        depth_in, weight_in, lendable_in, res_in = place_fair_drain_extras(
            mesh, depth_of, snapshot.weight_milli, lendable, res_of_fr
        )
        harness.note_place_seconds(_time.perf_counter() - t0p)
        harness.note_bucket(
            "fair_preempt_kernel",
            (
                queues_np["cells"].shape, plan.n_segments, plan.n_steps,
                plan.max_cycles,
            ),
            mesh,
        )
    else:
        tree_in, paths_in = tree, paths_j
        usage_in = jnp.asarray(snapshot.local_usage)
        queues = DrainQueues(
            **{k: jnp.asarray(v) for k, v in queues_np.items()}
        )
        victims = SegVictims(
            **{k: jnp.asarray(v) for k, v in victims_np.items()}
        )
        fairp = FairSegPanels(
            **{k: jnp.asarray(v) for k, v in fairp_np.items()}
        )
        depth_in = jnp.asarray(depth_of)
        weight_in = jnp.asarray(snapshot.weight_milli)
        lendable_in = jnp.asarray(lendable)
        res_in = jnp.asarray(res_of_fr)
    flat = np.asarray(
        solve_drain_fair_preempt_packed_jit(
            tree_in,
            usage_in,
            queues,
            victims,
            fairp,
            paths_in,
            depth_in,
            weight_in,
            lendable_in,
            res_in,
            n_segments=plan.n_segments,
            n_steps=plan.n_steps,
            max_cycles=plan.max_cycles,
            n_res=n_res,
            prio_tie=bool(_feature_enabled("PrioritySortingWithinCohort")),
            strategy1=strategy1,
            has_second=len(strategies) > 1,
        )
    )  # the single fetch
    return _preempt_outcome(plan, low, flat, queues_np, fair=True)


# caps keeping the TAS placement kernel's i32 prefix sums exact:
# MAX_TAS_COUNT * MAX_TAS_DOMAINS < 2^31 (drain_kernel.split). Gangs
# above a million pods or merged forests above 2048 leaves route to the
# host cycle loop.
MAX_TAS_COUNT = 1 << 20
MAX_TAS_DOMAINS = 1 << 11


def _merge_tas_forests(snaps, union_res, d_global):
    """Concatenate per-flavor topologies into ONE disjoint domain
    forest, aligned at the LEAF level.

    A flavor with fewer levels gets structural dummy TOP levels (one
    domain per missing level, chained) so seg_ids/parent maps stay
    rectangular; the dummies are semantically unreachable — the kernel
    clamps the preferred-mode relax-up at each flavor's real top
    (TASHeads.t_top). Returns (topo_free, tas_usage, seg_ids,
    n_domains, parent_map, leaf_flavor, leaf_off) on the union resource
    axis, or None when ``snaps`` is empty."""
    if not snaps:
        return None
    from kueue_tpu.ops.tas_kernel import _level_prefix_index

    n_res = max(len(union_res), 1)
    u_index = {r: j for j, r in enumerate(union_res)}
    n_f = len(snaps)
    idxs_of = []
    counts = []  # [F][D] domains per flavor per global level
    for s in snaps:
        df = len(s.level_keys)
        idxs = [_level_prefix_index(s, d) for d in range(df)]
        idxs_of.append(idxs)
        counts.append(
            [1] * (d_global - df) + [len(ix) for ix in idxs]
        )
    n_domains = tuple(
        sum(counts[f][d] for f in range(n_f)) for d in range(d_global)
    )
    dom_off = [[0] * n_f for _ in range(d_global)]
    for d in range(d_global):
        acc = 0
        for f in range(n_f):
            dom_off[d][f] = acc
            acc += counts[f][d]
    lf_total = sum(len(s._leaf_order) for s in snaps)
    nd_max = max(n_domains)
    seg_ids = np.zeros((d_global, lf_total), dtype=np.int32)
    parent_map = np.zeros((d_global, nd_max), dtype=np.int32)
    topo_free = np.zeros((lf_total, n_res), dtype=np.int64)
    tas_usage = np.zeros((lf_total, n_res), dtype=np.int64)
    leaf_flavor = np.zeros(lf_total, dtype=np.int32)
    leaf_off: Dict[int, int] = {}
    off_l = 0
    for f, s in enumerate(snaps):
        df = len(s.level_keys)
        lvl_off = d_global - df
        nl_f = len(s._leaf_order)
        idxs = idxs_of[f]
        leaf_off[f] = off_l
        leaf_flavor[off_l : off_l + nl_f] = f
        cols = [u_index[r] for r in s._resources]
        topo_free[off_l : off_l + nl_f, cols] = s._free
        tas_usage[off_l : off_l + nl_f, cols] = s._tas_usage
        for d in range(d_global):
            dl = d - lvl_off
            if dl < 0:
                seg_ids[d, off_l : off_l + nl_f] = dom_off[d][f]
            else:
                for i, leaf in enumerate(s._leaf_order):
                    seg_ids[d, off_l + i] = (
                        dom_off[d][f] + idxs[dl][leaf.level_values[: dl + 1]]
                    )
        for d in range(1, d_global):
            dl = d - lvl_off
            if dl < 0:
                parent_map[d, dom_off[d][f]] = dom_off[d - 1][f]
            elif dl == 0:
                for idx in idxs[0].values():
                    parent_map[d, dom_off[d][f] + idx] = dom_off[d - 1][f]
            else:
                for p, idx in idxs[dl].items():
                    parent_map[d, dom_off[d][f] + idx] = (
                        dom_off[d - 1][f] + idxs[dl - 1][p[:-1]]
                    )
        off_l += nl_f
    return (
        topo_free, tas_usage, seg_ids, n_domains, parent_map,
        leaf_flavor, leaf_off,
    )


@dataclass
class TASDrainOutcome(DrainOutcome):
    # TopologyAssignment per admitted entry, aligned with ``admitted``
    # (None for non-TAS workloads)
    assignments: List[object] = field(default_factory=list)


def run_drain_tas(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    tas_cache,
    max_candidates: int = 8,
    max_cells: int = 4,
    timestamp_fn=None,
    max_cycles: Optional[int] = None,
    mesh=None,  # jax.sharding.Mesh: shard the Q axis across devices
    policy=None,  # kueue_tpu/policy AdmissionPolicy
    now: float = 0.0,
) -> TASDrainOutcome:
    """Multi-cycle drain with Topology-Aware Scheduling heads decided
    on the device (ops/drain_kernel.solve_drain_tas) — one dispatch +
    one fetch, then a cheap host replay (one placement per ADMITTED
    workload, grouped per cycle against cycle-start state) that
    reconstructs the TopologyAssignments and asserts the kernel's final
    TAS leaf usage is reproduced exactly, flavor by flavor.

    With ``mesh`` the per-queue tensors (DrainQueues + TASHeads' Q
    rows) shard along ``wl`` and the merged domain forest stays
    replicated — every shard's queues place into the same forest and
    GSPMD resolves the sequential placement scan's leaf-usage scatters;
    the host replay's exactness assertion is unchanged and doubles as a
    per-drain mesh-parity check.

    Scope: single-podset topology requests in ALL THREE modes —
    Required, Preferred (level relaxation,
    tas_flavor_snapshot.go:513-549), Unconstrained — over ANY number of
    taint-free TAS flavors (queues segmented by flavor, each placing
    into its own subtree of one merged domain forest); TAS
    ClusterQueues must be preemption-free and single-flavor, and the
    default BestFit profile applies (the gated Most/LeastFree profiles
    stay host-side). Topology requests on non-TAS ClusterQueues PARK in
    kernel at the exact cycle the host would reject the flavor. Heads
    outside the scope route to ``fallback`` for the cycle loop.
    """
    from kueue_tpu._jax import jnp
    from kueue_tpu.core.workload_info import quota_per_pod
    from kueue_tpu.models.constants import (
        TOPOLOGY_MODE_PREFERRED,
        TOPOLOGY_MODE_REQUIRED,
        TOPOLOGY_MODE_UNCONSTRAINED,
        PreemptionPolicy,
        ReclaimWithinCohortPolicy,
    )
    from kueue_tpu.ops.drain_kernel import (
        DrainQueues,
        TASHeads,
        solve_drain_tas_packed_jit,
    )
    from kueue_tpu.resources import PODS
    from kueue_tpu.tas.snapshot import TASPodSetRequest, domain_id

    plan = plan_drain(
        snapshot, pending, flavors, max_candidates, max_cells, timestamp_fn,
        allow_tas=True, policy=policy, now=now,
    )
    q = max(len(plan.cq_order), 1)
    nl = plan.queues_np["cells"].shape[1]

    tas_flavor_names = set(tas_cache.flavors)
    TAS_MODE_ID = {
        TOPOLOGY_MODE_REQUIRED: 0,
        TOPOLOGY_MODE_PREFERRED: 1,
        TOPOLOGY_MODE_UNCONSTRAINED: 2,
    }

    def cq_flavor_names(cq_name):
        cq = snapshot.cq_models[cq_name]
        return {fq.name for rg in cq.resource_groups for fq in rg.flavors}

    # ---- scope: classify queues; EVERY in-scope TAS flavor joins the
    # merged domain forest (queues segmented by flavor) ----
    drop: List[int] = []
    tas_queue: Dict[int, str] = {}  # qi -> tas flavor name
    t_bad = np.zeros((q, nl), dtype=bool)
    for qi, cq_name in enumerate(plan.cq_order):
        prem = snapshot.cq_models[cq_name].preemption
        names = cq_flavor_names(cq_name)
        tnames = names & tas_flavor_names
        if not tnames:
            # plain quota queue — topology-requesting entries on a
            # non-TAS flavor are PARKED in kernel at the exact cycle
            # the host would reject the flavor ("does not support
            # TopologyAwareScheduling", tas/manager.py check); the
            # queue itself stays in the drain
            for pos in range(int(plan.queues_np["qlen"][qi])):
                i = plan.head_of.get((qi, pos))
                if i is not None and any(
                    ps.topology_request is not None
                    for ps in plan.lowered.heads[i].pod_sets
                ):
                    t_bad[qi, pos] = True
            continue
        capable = prem.within_cluster_queue != PreemptionPolicy.NEVER or (
            snapshot.has_cohort(cq_name)
            and prem.reclaim_within_cohort != ReclaimWithinCohortPolicy.NEVER
        )
        if capable or len(names) != 1:
            drop.append(qi)
            continue
        tas_queue[qi] = next(iter(tnames))

    # per-flavor snapshots; tainted flavors stay host-side (the kernel
    # has no toleration filtering), and the merged forest caps its
    # domain axis so the placement kernel's i32 prefix sums stay exact
    # (MAX_TAS_COUNT x MAX_TAS_DOMAINS < 2^31 — see drain_kernel.split)
    flavor_names = sorted(set(tas_queue.values()))
    snaps: Dict[str, object] = {}
    total_leaves = 0
    for fname in flavor_names:
        s = tas_cache.flavors[fname].snapshot()
        s.freeze()
        over = total_leaves + len(s._leaf_order) > MAX_TAS_DOMAINS
        if over or any(t for t in s._leaf_taints):
            for qi in [k for k, v in tas_queue.items() if v == fname]:
                drop.append(qi)
                del tas_queue[qi]
        else:
            total_leaves += len(s._leaf_order)
            snaps[fname] = s
    flavor_names = sorted(snaps)
    flavor_idx = {f: i for i, f in enumerate(flavor_names)}

    # union resource axis + merged level depth
    union_res = sorted(
        {r for s in snaps.values() for r in s._resources}
    )
    n_res_t = max(len(union_res), 1)
    u_index = {r: j for j, r in enumerate(union_res)}
    d_global = max(
        (len(s.level_keys) for s in snaps.values()), default=1
    )

    # per-entry TAS lowering + scope checks
    t_is = np.zeros(q, dtype=bool)
    t_req = np.zeros((q, nl, n_res_t), dtype=np.int64)
    t_count = np.zeros((q, nl), dtype=np.int32)
    t_level = np.zeros((q, nl), dtype=np.int32)
    t_mode = np.zeros((q, nl), dtype=np.int32)
    t_top = np.zeros(q, dtype=np.int32)
    t_flavor = np.zeros(q, dtype=np.int32)
    dropped = set(drop)
    for qi, fname in tas_queue.items():
        if qi in dropped:
            continue
        snap_f = snaps[fname]
        lvl_off = d_global - len(snap_f.level_keys)
        r_index_f = set(snap_f._resources)
        ok = True
        for pos in range(int(plan.queues_np["qlen"][qi])):
            i = plan.head_of.get((qi, pos))
            if i is None:
                continue
            wl = plan.lowered.heads[i]
            if len(wl.pod_sets) != 1:
                ok = False
                break
            ps = wl.pod_sets[0]
            tr = ps.topology_request
            if tr is None or tr.mode not in TAS_MODE_ID:
                ok = False
                break
            if tr.mode == TOPOLOGY_MODE_UNCONSTRAINED:
                lvl_local = len(snap_f.level_keys) - 1  # lowest level
            elif tr.level in snap_f.level_keys:
                lvl_local = snap_f.level_keys.index(tr.level)
            else:
                ok = False
                break
            per_pod = dict(quota_per_pod(ps, None))
            per_pod[PODS] = per_pod.get(PODS, 0) + 1
            if any(r not in r_index_f for r in per_pod):
                ok = False
                break
            if int(ps.count) > MAX_TAS_COUNT:
                ok = False  # keeps the kernel's i32 prefix sums exact
                break
            for r, v in per_pod.items():
                t_req[qi, pos, u_index[r]] = int(v)
            t_count[qi, pos] = int(ps.count)
            t_level[qi, pos] = lvl_off + lvl_local
            t_mode[qi, pos] = TAS_MODE_ID[tr.mode]
        if not ok:
            drop.append(qi)
            dropped.add(qi)
        else:
            t_is[qi] = True
            t_top[qi] = d_global - len(snap_f.level_keys)
            t_flavor[qi] = flavor_idx[fname]

    # drop out-of-scope queues to the fallback path
    extra_fb: List[Tuple[Workload, str]] = []
    for qi in sorted(set(drop)):
        plan.queues_np["qlen"][qi] = 0
        plan.queues_np["cq_rows"][qi] = -1
        plan.queues_np["seg_id"][qi] = -1
        for pos in range(nl):
            i = plan.head_of.pop((qi, pos), None)
            if i is not None:
                extra_fb.append(
                    (plan.lowered.heads[i], plan.lowered.cq_names[i])
                )

    if max_cycles is not None:
        plan.max_cycles = max_cycles
    tree, paths, _ = tree_arrays(snapshot)

    live_flavors = sorted(
        {tas_queue[qi] for qi in tas_queue if qi not in dropped}
    )
    merged = _merge_tas_forests(
        [snaps[f] for f in live_flavors], union_res, d_global
    )
    if merged is not None:
        (topo_free_np, tas_usage0_np, seg_ids_np, n_domains, parent_map,
         leaf_flavor_np, leaf_off) = merged
        # remap queue flavor ids onto the LIVE flavor axis
        live_idx = {f: i for i, f in enumerate(live_flavors)}
        for qi, fname in tas_queue.items():
            if qi not in dropped:
                t_flavor[qi] = live_idx[fname]
        lf_n = topo_free_np.shape[0]
    else:
        # no TAS queue in scope: inert 1-leaf topology
        topo_free_np = np.zeros((1, 1), dtype=np.int64)
        tas_usage0_np = np.zeros((1, 1), dtype=np.int64)
        seg_ids_np = np.zeros((1, 1), dtype=np.int32)
        n_domains = (1,)
        parent_map = np.zeros((1, 1), dtype=np.int32)
        leaf_flavor_np = np.zeros(1, dtype=np.int32)
        leaf_off = {}
        lf_n = 1
        n_res_t = max(n_res_t, 1)
        t_req = t_req[:, :, :1]

    theads_np = dict(
        t_is=t_is, t_req=t_req, t_count=t_count, t_level=t_level,
        t_mode=t_mode, t_top=t_top, t_flavor=t_flavor,
        leaf_flavor=leaf_flavor_np, parent_map=parent_map, t_bad=t_bad,
    )
    n_live = int((plan.queues_np["cq_rows"] >= 0).sum())
    n_steps = _bucket(max(n_live, 1), minimum=8)

    queues_np = plan.queues_np
    if mesh is not None:
        import time as _time

        from kueue_tpu.parallel import harness
        from kueue_tpu.parallel.sharded_solver import (
            pad_queue_arrays,
            pad_tas_arrays,
            place_tas_drain_inputs,
        )

        t0p = _time.perf_counter()
        queues_np = pad_queue_arrays(queues_np, mesh.shape["wl"])
        theads_np = pad_tas_arrays(theads_np, queues_np["qlen"].shape[0])
        (tree_in, usage_in, queues, paths_in, topo_in, tusage_in,
         seg_in, theads) = place_tas_drain_inputs(
            mesh, tree, snapshot.local_usage, DrainQueues(**queues_np),
            paths, topo_free_np, tas_usage0_np, seg_ids_np,
            TASHeads(**theads_np),
        )
        harness.note_place_seconds(_time.perf_counter() - t0p)
        harness.note_bucket(
            "tas_kernel",
            (
                queues_np["cells"].shape, tuple(n_domains), n_steps,
                plan.max_cycles,
            ),
            mesh,
        )
    else:
        tree_in, paths_in = tree, paths
        usage_in = jnp.asarray(snapshot.local_usage)
        queues = DrainQueues(
            **{k: jnp.asarray(v) for k, v in queues_np.items()}
        )
        topo_in = jnp.asarray(topo_free_np)
        tusage_in = jnp.asarray(tas_usage0_np)
        seg_in = jnp.asarray(seg_ids_np)
        theads = TASHeads(
            **{k: jnp.asarray(v) for k, v in theads_np.items()}
        )

    flat = np.asarray(
        solve_drain_tas_packed_jit(
            tree_in,
            usage_in,
            queues,
            paths_in,
            topo_in,
            tusage_in,
            seg_in,
            theads,
            n_domains=n_domains,
            n_steps=n_steps,
            max_cycles=plan.max_cycles,
        )
    )  # the single fetch
    nq, nl2, npd = queues_np["cells"].shape[:3]
    ql, qlp = nq * nl2, nq * nl2 * npd
    off = 0
    adm_k = flat[off : off + qlp].reshape((nq, nl2, npd)); off += qlp
    adm_cycle = flat[off : off + ql].reshape((nq, nl2)); off += ql
    adm_step = flat[off : off + ql].reshape((nq, nl2)); off += ql
    cursor = flat[off : off + nq]; off += nq
    stuck_q = flat[off : off + nq].astype(bool); off += nq
    tas_final = flat[off : off + lf_n * n_res_t].reshape((lf_n, n_res_t))
    off += lf_n * n_res_t
    cycles = int(flat[-1])
    qlen = queues_np["qlen"]
    truncated = bool(np.any((cursor < qlen) & ~stuck_q))

    lowered = plan.lowered
    admitted: List[Tuple[Workload, str, Dict[str, str], int]] = []
    adm_meta: List[Tuple[int, int, int]] = []  # (cycle, step, index)
    parked: List[Tuple[Workload, str]] = []
    extra_fallback: List[Tuple[Workload, str]] = list(extra_fb)
    for (qi, pos), i in plan.head_of.items():
        wl = lowered.heads[i]
        cq_name = lowered.cq_names[i]
        kk = int(adm_k[qi, pos, 0])
        if kk >= 0:
            adm_meta.append(
                (int(adm_cycle[qi, pos]), int(adm_step[qi, pos]), len(admitted))
            )
            admitted.append(
                (wl, cq_name, _admitted_flavors(lowered, i, adm_k[qi, pos]),
                 int(adm_cycle[qi, pos]))
            )
        elif pos >= int(cursor[qi]):
            extra_fallback.append((wl, cq_name))
        else:
            parked.append((wl, cq_name))
    order = sorted(range(len(admitted)), key=lambda j: adm_meta[j][:2])
    admitted = [admitted[adm_meta[j][2]] for j in order]
    adm_meta = [adm_meta[j] for j in order]

    # ---- replay: reconstruct TopologyAssignments per admission cycle
    # against cycle-start state (the kernel nominates against it too),
    # per FLAVOR; the final leaf usage must reproduce the kernel's
    # exactly, flavor by flavor ----
    assignments: List[object] = [None] * len(admitted)
    if live_flavors:
        live_idx = {f: i for i, f in enumerate(live_flavors)}
        flavor_of_cq = {
            plan.cq_order[qi]: fname
            for qi, fname in tas_queue.items()
            if qi not in dropped
        }
        j = 0
        while j < len(admitted):
            cyc = adm_meta[j][0]
            batch = []
            while j < len(admitted) and adm_meta[j][0] == cyc:
                wl, cq_name, _, _ = admitted[j]
                if (
                    wl.pod_sets[0].topology_request is not None
                    and cq_name in flavor_of_cq
                ):
                    batch.append(j)
                j += 1
            placed = []
            for bj in batch:
                wl, cq_name = admitted[bj][0], admitted[bj][1]
                sf = snaps[flavor_of_cq[cq_name]]
                ps = wl.pod_sets[0]
                req = TASPodSetRequest(
                    podset_name=ps.name,
                    count=ps.count,
                    single_pod_requests=dict(quota_per_pod(ps, None)),
                    topology_request=ps.topology_request,
                    tolerations=tuple(ps.tolerations),
                )
                ta, reason = sf.find_topology_assignment(req, {})
                if reason:  # explicit raise: must survive `python -O`
                    raise AssertionError(
                        f"TAS drain replay failed for {wl.name}: {reason}"
                    )
                assignments[bj] = ta
                placed.append((sf, req, ta))
            for sf, req, ta in placed:  # charge AFTER the batch
                for dom in ta.domains:
                    did = domain_id(dom.values)
                    usage = {
                        r: v * dom.count
                        for r, v in req.single_pod_requests.items()
                    }
                    sf.add_tas_usage(did, usage, dom.count)
        for fname in live_flavors:
            sf = snaps[fname]
            sf.freeze()
            off = leaf_off[live_idx[fname]]
            nl_f = len(sf._leaf_order)
            cols = [u_index[r] for r in sf._resources]
            sub = tas_final[off : off + nl_f][:, cols]
            if not np.array_equal(sf._tas_usage, sub):
                bad = np.argwhere(sf._tas_usage != sub)[:8]
                raise AssertionError(
                    f"TAS drain replay does not reproduce the kernel's "
                    f"leaf usage for flavor {fname} — placement parity "
                    "bug; first diffs (leaf, res): "
                    + "; ".join(
                        f"{tuple(ix)}: host={sf._tas_usage[tuple(ix)]} "
                        f"kernel={sub[tuple(ix)]}"
                        for ix in bad
                    )
                )

    fb = [
        (lowered.heads[i], lowered.cq_names[i]) for i in plan.fallback
    ] + extra_fallback
    return TASDrainOutcome(
        admitted=admitted,
        parked=parked,
        fallback=fb,
        cycles=cycles,
        truncated=truncated,
        assignments=assignments,
    )


@dataclass
class DrainLaunch:
    """An in-flight plain-drain device dispatch (launch/fetch split).

    ``launch_drain`` dispatches the packed solve and returns
    immediately — JAX's async dispatch keeps the device working while
    the host does something else (the pipelined drain loop applies the
    PREVIOUS round's outcome inside this window, core/pipeline.py).
    ``fetch()`` blocks on the ONE result fetch and maps decisions back
    to workloads. Nothing between construction and fetch touches
    runtime state, so an unfetched launch is always safe to discard
    (the pipeline's conflict-miss path)."""

    plan: DrainPlan
    queues_np: dict
    flat_dev: object  # unfetched device array
    usage_shape: Tuple[int, int]
    extra_fb_entries: List[Tuple[Workload, str]] = field(default_factory=list)
    # the exact backlog this launch solves, in per-CQ heap order — the
    # pipeline's commit check compares it against the real post-apply
    # backlog before trusting a speculative launch
    pending: Optional[List[Tuple[Workload, str]]] = None
    max_cycles: Optional[int] = None

    def fetch(self) -> DrainOutcome:
        flat = np.asarray(self.flat_dev)  # the single fetch
        nq, nl, npd = self.queues_np["cells"].shape[:3]  # incl. padding
        ql = nq * nl
        qlp = nq * nl * npd
        adm_k = flat[:qlp].reshape((nq, nl, npd))
        adm_cycle = flat[qlp : qlp + ql].reshape((nq, nl))
        cursor = flat[qlp + ql : qlp + ql + nq]
        stuck_q = flat[qlp + ql + nq : qlp + ql + 2 * nq].astype(bool)
        off = qlp + ql + 2 * nq
        n_u = int(self.usage_shape[0]) * int(self.usage_shape[1])
        final_usage = flat[off : off + n_u].reshape(self.usage_shape)
        cycles = int(flat[-1])
        return _map_drain_result(
            self.plan, adm_k, adm_cycle, cursor, stuck_q, cycles,
            self.queues_np, self.extra_fb_entries,
            final_usage=final_usage,
        )


def launch_drain(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 4,
    timestamp_fn=None,
    max_cycles: Optional[int] = None,
    mesh=None,  # jax.sharding.Mesh: shard the Q axis across devices
    resident=None,  # core.encode.ResidentEncoder (single-device only)
    policy=None,  # kueue_tpu/policy AdmissionPolicy (scored admission)
    now: float = 0.0,
) -> DrainLaunch:
    """Plan + DISPATCH the plain device drain without fetching — the
    async half of ``run_drain`` (device, no fair sharing: the pipelined
    hot path). ``run_drain(...) == launch_drain(...).fetch()`` for that
    configuration, by construction.

    With ``mesh`` the per-queue tensors shard along the mesh's ``wl``
    axis exactly like ``run_drain(mesh=...)`` — prefetched pipelined
    launches ride the same sharded path as blocking solves. With
    ``resident`` (single-device only; ignored under a mesh) the quota
    tree + paths stay device-resident between rounds and only changed
    leaf-usage rows ship (core/encode.ResidentEncoder)."""
    import time as _time

    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.drain_kernel import DrainQueues, solve_drain_packed_jit

    plan = plan_drain(
        snapshot, pending, flavors, max_candidates, max_cells, timestamp_fn,
        policy=policy, now=now,
    )
    if max_cycles is not None:
        plan.max_cycles = max_cycles
    queues_np = plan.queues_np
    if mesh is not None:
        if resident is not None:
            # documented single-device-only: reject loudly instead of
            # silently ignoring the resident buffers (the mesh path
            # re-places inputs with their shardings every round —
            # device_put onto shards IS its transfer plan)
            raise ValueError(
                "launch_drain(resident=...) is single-device only: the "
                "mesh path re-places inputs with their shardings every "
                "round — pass resident=None under a mesh"
            )
        from kueue_tpu.parallel import harness
        from kueue_tpu.parallel.sharded_solver import (
            pad_queue_arrays,
            place_drain_inputs,
        )

        tree, paths, _ = tree_arrays(snapshot)
        t0p = _time.perf_counter()
        queues_np = pad_queue_arrays(queues_np, mesh.shape["wl"])
        tree, usage_in, queues, paths = place_drain_inputs(
            mesh, tree, snapshot.local_usage, DrainQueues(**queues_np), paths
        )
        harness.note_place_seconds(_time.perf_counter() - t0p)
        harness.note_bucket(
            "drain_kernel",
            (
                queues_np["cells"].shape, plan.n_segments, plan.n_steps,
                plan.max_cycles,
            ),
            mesh,
        )
    else:
        if resident is not None:
            tree, paths, usage_in = resident.refresh(snapshot)
        else:
            tree, paths, _ = tree_arrays(snapshot)
            usage_in = jnp.asarray(snapshot.local_usage)
        queues = DrainQueues(
            **{k: jnp.asarray(v) for k, v in queues_np.items()}
        )
    flat_dev = solve_drain_packed_jit(
        tree,
        usage_in,
        queues,
        paths,
        n_segments=plan.n_segments,
        n_steps=plan.n_steps,
        max_cycles=plan.max_cycles,
    )
    return DrainLaunch(
        plan=plan,
        queues_np=queues_np,
        flat_dev=flat_dev,
        usage_shape=tuple(snapshot.local_usage.shape),
        pending=list(pending),
        max_cycles=plan.max_cycles,
    )


def _cap_suffix_of(plan: DrainPlan) -> np.ndarray:
    """int32[Q, L] suffix retry budgets: ``cap[q, p]`` is the
    ``retry_cap`` a fresh ``plan_drain`` over the queue's entries at
    positions >= p would compute (min(4096, max walk_states + 1)) —
    what the megaloop gathers at each round boundary so its in-kernel
    continuation budgets match a serial re-plan's. Column 0 equals
    ``plan.queues_np['retry_cap']`` by construction."""
    q = plan.queues_np["qlen"].shape[0]
    l = plan.queues_np["cells"].shape[1]
    cap = np.zeros((q, l), dtype=np.int32)
    per_q: Dict[int, List[Tuple[int, int]]] = {}
    for (qi, pos), i in plan.head_of.items():
        per_q.setdefault(qi, []).append((pos, i))
    ws = plan.lowered.walk_states
    for qi, items in per_q.items():
        items.sort()
        vals = np.array([ws[i] for _, i in items], dtype=np.int64)
        sfx = np.maximum.accumulate(vals[::-1])[::-1]
        cap[qi, : len(items)] = np.minimum(4096, sfx + 1).astype(np.int32)
    return cap


@dataclass
class MegaloopLog:
    """The host-decoded round-stamped decision log of one fused launch:
    one DrainOutcome per executed round, in round order — exactly the
    sequence of outcomes K serial ``launch_drain(max_cycles=chunk)``
    rounds would have fetched (asserted against the serial mirror in
    tests/test_megaloop.py). ``truncated`` means the final round still
    left entries undecided: the megaloop exhausted its round budget and
    the caller relaunches from the real post-apply state."""

    rounds: List[DrainOutcome]
    n_rounds: int
    cycles: int
    truncated: bool


@dataclass
class MegaloopLaunch:
    """An in-flight fused megaloop dispatch (the launch/fetch split of
    ``launch_drain`` extended to K rounds): ONE dispatch, ONE fetch for
    the whole batch. Nothing between construction and ``fetch`` touches
    runtime state, so an unfetched launch is always safe to discard."""

    plan: DrainPlan
    queues_np: dict
    flat_dev: object  # unfetched device array (the packed log)
    usage_shape: Tuple[int, int]
    start_usage: np.ndarray  # launch-time leaf usage (row-0 fallback)
    pending: List[Tuple[Workload, str]]
    chunk_cycles: int
    max_rounds: int

    def _usage_offset(self, r: int) -> int:
        q, l, p = self.queues_np["cells"].shape[:3]
        n, fr = self.usage_shape
        return (
            q * l * p + 2 * q * l + 2 * self.max_rounds * q
            + self.max_rounds + r * n * fr
        )

    def usage_dev(self, r: int):
        """Round r's final leaf usage as a DEVICE slice of the packed
        log — the in-loop usage carry the ResidentEncoder adopts after
        a fully-committed launch (no host round trip)."""
        n, fr = self.usage_shape
        off = self._usage_offset(r)
        return self.flat_dev[off : off + n * fr].reshape((n, fr))

    def fetch(self) -> MegaloopLog:
        flat = np.asarray(self.flat_dev)  # the single fetch
        q, l, p = self.queues_np["cells"].shape[:3]
        n, fr = self.usage_shape
        rr = self.max_rounds
        qlp, ql = q * l * p, q * l
        off = 0
        adm_k = flat[off : off + qlp].reshape((q, l, p)); off += qlp
        adm_cycle = flat[off : off + ql].reshape((q, l)); off += ql
        adm_round = flat[off : off + ql].reshape((q, l)); off += ql
        r_cursor = flat[off : off + rr * q].reshape((rr, q)); off += rr * q
        r_stuck = (
            flat[off : off + rr * q].reshape((rr, q)).astype(bool)
        ); off += rr * q
        r_cycles = flat[off : off + rr]; off += rr
        r_usage = flat[off : off + rr * n * fr].reshape((rr, n, fr))
        n_rounds = int(flat[-2])
        cycles = int(flat[-1])
        rounds = _map_megaloop_rounds(
            self.plan, self.queues_np, adm_k, adm_cycle, adm_round,
            r_cursor, r_stuck, r_cycles, r_usage, n_rounds,
            self.start_usage,
        )
        return MegaloopLog(
            rounds=rounds,
            n_rounds=n_rounds,
            cycles=cycles,
            truncated=bool(rounds and rounds[-1].undecided),
        )


def _map_megaloop_rounds(
    plan: DrainPlan,
    queues_np: dict,
    adm_k,
    adm_cycle,
    adm_round,
    r_cursor,
    r_stuck,
    r_cycles,
    r_usage,
    n_rounds: int,
    start_usage: np.ndarray,
) -> List[DrainOutcome]:
    """Slice the fused log into per-round DrainOutcomes — each
    bit-for-bit what ``_map_drain_result`` would have produced for a
    serial round launched over the previous round's undecided backlog:
    round scope is the entries past the previous cursor in queues not
    yet retired; unreached entries route to fallback (and to
    ``undecided`` unless their queue went stuck); the structural
    ``plan.fallback`` set belongs to round 0 only (later serial rounds
    are planned over undecided entries, all representable)."""
    lowered = plan.lowered
    q = queues_np["qlen"].shape[0]
    rounds: List[DrainOutcome] = []
    prev_cursor = np.zeros(q, dtype=np.int64)
    prev_dead = np.zeros(q, dtype=bool)
    for r in range(max(int(n_rounds), 1)):
        ran = r < int(n_rounds)
        cursor_r = np.asarray(r_cursor[r] if ran else prev_cursor)
        stuck_r = (
            np.asarray(r_stuck[r]).astype(bool) if ran else prev_dead
        )
        cycles_r = int(r_cycles[r]) if ran else 0
        usage_r = (
            np.asarray(r_usage[r]) if ran else np.asarray(start_usage)
        )
        admitted: List[Tuple[Workload, str, Dict[str, str], int]] = []
        parked: List[Tuple[Workload, str]] = []
        fb_extra: List[Tuple[Workload, str]] = []
        undecided: List[Tuple[Workload, str]] = []
        for (qi, pos), i in plan.head_of.items():
            if prev_dead[qi] or pos < prev_cursor[qi]:
                continue  # decided (or retired) in an earlier round
            wl = lowered.heads[i]
            cq_name = lowered.cq_names[i]
            if int(adm_round[qi, pos]) == r:
                admitted.append(
                    (wl, cq_name,
                     _admitted_flavors(lowered, i, adm_k[qi, pos]),
                     int(adm_cycle[qi, pos]))
                )
            elif pos >= int(cursor_r[qi]):
                # never processed this round: no decision; stuck-frozen
                # queues are terminal, the rest feed the next round
                fb_extra.append((wl, cq_name))
                if not stuck_r[qi]:
                    undecided.append((wl, cq_name))
            else:
                parked.append((wl, cq_name))
        admitted.sort(key=lambda t: t[3])
        fb = (
            [(lowered.heads[i], lowered.cq_names[i]) for i in plan.fallback]
            if r == 0
            else []
        ) + fb_extra
        rounds.append(
            DrainOutcome(
                admitted=admitted,
                parked=parked,
                fallback=fb,
                cycles=cycles_r,
                truncated=bool(undecided),
                undecided=undecided,
                final_usage=usage_r.astype(np.int64, copy=False),
            )
        )
        prev_cursor = cursor_r.astype(np.int64).copy()
        prev_dead = prev_dead | stuck_r
    return rounds


def launch_drain_megaloop(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 4,
    timestamp_fn=None,
    chunk_cycles: int = 16,
    max_rounds: int = 8,
    mesh=None,  # jax.sharding.Mesh: shard the Q axis across devices
    resident=None,  # core.encode.ResidentEncoder (single-device only)
    policy=None,  # kueue_tpu/policy AdmissionPolicy (scored admission)
    now: float = 0.0,
) -> MegaloopLaunch:
    """Plan + DISPATCH the fused K-round megaloop without fetching —
    ``launch_drain`` with the host round trip amortized over up to
    ``max_rounds`` drain rounds of ``chunk_cycles`` kernel cycles each
    (ops/megaloop_kernel.solve_drain_megaloop). The policy score
    tensors flow through ``plan_drain`` unchanged, so the megaloop is
    policy-complete, not a first-fit fast path.

    With ``mesh`` the per-queue tensors (and the suffix retry budgets)
    shard along ``wl`` exactly like ``launch_drain(mesh=...)``. With
    ``resident`` (single-device only; a passed resident under a mesh
    raises — see launch_drain) the quota tree + paths stay
    device-resident between launches."""
    import time as _time

    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.drain_kernel import DrainQueues
    from kueue_tpu.ops.megaloop_kernel import (
        solve_drain_megaloop_packed_jit,
    )

    plan = plan_drain(
        snapshot, pending, flavors, max_candidates, max_cells, timestamp_fn,
        policy=policy, now=now,
    )
    cap_suffix = _cap_suffix_of(plan)
    queues_np = plan.queues_np
    if mesh is not None:
        if resident is not None:
            raise ValueError(
                "launch_drain_megaloop(resident=...) is single-device "
                "only: the mesh path re-places inputs with their "
                "shardings every launch (device_put onto shards IS its "
                "transfer plan) — pass resident=None under a mesh"
            )
        from kueue_tpu.parallel import harness
        from kueue_tpu.parallel.sharded_solver import (
            _sh,
            pad_queue_arrays,
            place_drain_inputs,
        )

        t0p = _time.perf_counter()
        mult = mesh.shape["wl"]
        queues_np = pad_queue_arrays(queues_np, mult)
        q_pad = queues_np["qlen"].shape[0]
        if cap_suffix.shape[0] < q_pad:
            cap_suffix = np.concatenate(
                [
                    cap_suffix,
                    np.zeros(
                        (q_pad - cap_suffix.shape[0], cap_suffix.shape[1]),
                        dtype=cap_suffix.dtype,
                    ),
                ]
            )
        tree, paths, _ = tree_arrays(snapshot)
        tree, usage_in, queues, paths = place_drain_inputs(
            mesh, tree, snapshot.local_usage, DrainQueues(**queues_np), paths
        )
        from kueue_tpu._jax import jax as _jax

        cap_in = _jax.device_put(cap_suffix, _sh(mesh, "wl", None))
        harness.note_place_seconds(_time.perf_counter() - t0p)
        harness.note_bucket(
            "megaloop_kernel",
            (
                queues_np["cells"].shape, plan.n_segments, plan.n_steps,
                chunk_cycles, max_rounds,
            ),
            mesh,
        )
    else:
        if resident is not None:
            tree, paths, usage_in = resident.refresh(snapshot)
        else:
            tree, paths, _ = tree_arrays(snapshot)
            usage_in = jnp.asarray(snapshot.local_usage)
        queues = DrainQueues(
            **{k: jnp.asarray(v) for k, v in queues_np.items()}
        )
        cap_in = jnp.asarray(cap_suffix)
    flat_dev = solve_drain_megaloop_packed_jit(
        tree,
        usage_in,
        queues,
        paths,
        cap_in,
        n_segments=plan.n_segments,
        n_steps=plan.n_steps,
        chunk_cycles=int(chunk_cycles),
        max_rounds=int(max_rounds),
    )
    return MegaloopLaunch(
        plan=plan,
        queues_np=queues_np,
        flat_dev=flat_dev,
        usage_shape=tuple(snapshot.local_usage.shape),
        start_usage=np.asarray(snapshot.local_usage),
        pending=list(pending),
        chunk_cycles=int(chunk_cycles),
        max_rounds=int(max_rounds),
    )


def run_drain_megaloop_host(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 4,
    timestamp_fn=None,
    chunk_cycles: int = 16,
    max_rounds: int = 8,
    policy=None,
    now: float = 0.0,
) -> MegaloopLog:
    """The megaloop's numpy HOST AUTHORITY twin over the identical
    plan tensors (ops/megaloop_np.solve_megaloop_np — which IS the
    serial chunked loop over suffix-trimmed queues), decoded through
    the same ``_map_megaloop_rounds``. Bit-for-bit the device log's
    decisions, property-tested in tests/test_megaloop.py."""
    from kueue_tpu.core.encode import encode_snapshot
    from kueue_tpu.ops.assign_kernel import build_paths
    from kueue_tpu.ops.megaloop_np import solve_megaloop_np

    plan = plan_drain(
        snapshot, pending, flavors, max_candidates, max_cells, timestamp_fn,
        policy=policy, now=now,
    )
    cap_suffix = _cap_suffix_of(plan)
    enc = encode_snapshot(snapshot)
    paths_np = build_paths(enc.parent, enc.max_depth)
    host = solve_megaloop_np(
        enc.parent,
        enc.level_mask,
        enc.nominal.astype(np.int64, copy=False),
        enc.lending_limit.astype(np.int64, copy=False),
        enc.borrowing_limit.astype(np.int64, copy=False),
        enc.local_usage.astype(np.int64, copy=False),
        plan.queues_np,
        paths_np,
        enc.max_depth,
        int(chunk_cycles),
        int(max_rounds),
        cap_suffix,
    )
    rounds = _map_megaloop_rounds(
        plan, plan.queues_np, host.admitted_k, host.admitted_cycle,
        host.admitted_round, host.round_cursor, host.round_stuck,
        host.round_cycles, host.round_usage, host.rounds,
        np.asarray(snapshot.local_usage),
    )
    return MegaloopLog(
        rounds=rounds,
        n_rounds=host.rounds,
        cycles=host.cycles,
        truncated=bool(rounds and rounds[-1].undecided),
    )


def run_drain(
    snapshot: Snapshot,
    pending: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 4,
    timestamp_fn=None,
    max_cycles: Optional[int] = None,
    mesh=None,  # jax.sharding.Mesh: shard the Q axis across devices
    fair_sharing: bool = False,
    use_device: bool = True,
    policy=None,  # kueue_tpu/policy AdmissionPolicy (scored admission)
    now: float = 0.0,
) -> DrainOutcome:
    """Plan + solve + map back, with one device round trip.

    ``max_cycles`` overrides the computed backstop (operators capping
    device time; tests exercising truncation routing). With ``mesh``
    the per-queue tensors are sharded along the mesh's ``wl`` axis
    (each device owns a slice of the ClusterQueues). With
    ``fair_sharing`` the cycle's admission order is the fair-sharing
    cohort tournament run ON DEVICE (ops/drain_kernel.solve_drain_fair)
    instead of the (borrowing, priority, FIFO) sort; preempt-capable
    ClusterQueues route to ``fallback`` in fair mode (use
    run_drain_fair_preempt for fair preemption in the drain). With
    ``mesh`` the per-queue tensors (and the fair DRS chain work) are
    sharded along ``wl``; node-space tensors stay replicated — separate
    root cohorts are independent subproblems, so the tournament's
    segment reductions parallelize and GSPMD resolves the node-space
    scatters.

    ``use_device=False`` solves the IDENTICAL plan on the numpy host
    mirror (ops/drain_np.solve_drain_np) — bit-for-bit the same
    decisions, property-tested across seeded random snapshots
    (tests/test_drain_parity.py). Plain scope only: the fair
    tournament keeps the kernel as its single implementation."""
    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.drain_kernel import DrainQueues, solve_drain_packed_jit

    if not use_device and (fair_sharing or mesh is not None):
        raise ValueError(
            "use_device=False covers the plain drain only (no fair "
            "tournament, no mesh sharding)"
        )
    plan = plan_drain(
        snapshot, pending, flavors, max_candidates, max_cells, timestamp_fn,
        policy=policy, now=now,
    )
    extra_fb_entries: List[Tuple[Workload, str]] = []
    if fair_sharing:
        # the in-kernel tournament orders admissions; fair PREEMPTION
        # stays on the per-cycle batched path, so preempt-capable CQs
        # fall back to the cycle loop
        from kueue_tpu.models.constants import (
            PreemptionPolicy,
            ReclaimWithinCohortPolicy,
        )

        for qi, cq_name in enumerate(plan.cq_order):
            prem = snapshot.cq_models[cq_name].preemption
            capable = (
                prem.within_cluster_queue != PreemptionPolicy.NEVER
                or (
                    snapshot.has_cohort(cq_name)
                    and prem.reclaim_within_cohort
                    != ReclaimWithinCohortPolicy.NEVER
                )
            )
            if not capable:
                continue
            plan.queues_np["qlen"][qi] = 0
            plan.queues_np["cq_rows"][qi] = -1
            plan.queues_np["seg_id"][qi] = -1
            for pos in range(plan.queues_np["cells"].shape[1]):
                i = plan.head_of.pop((qi, pos), None)
                if i is not None:
                    extra_fb_entries.append(
                        (plan.lowered.heads[i], plan.lowered.cq_names[i])
                    )
    if max_cycles is not None:
        plan.max_cycles = max_cycles
    if not use_device:
        # the numpy twin over the identical plan tensors — the guard's
        # host-authority drain and the parity property-test surface
        from kueue_tpu.core.encode import encode_snapshot
        from kueue_tpu.ops.assign_kernel import build_paths
        from kueue_tpu.ops.drain_np import solve_drain_np

        enc = encode_snapshot(snapshot)
        paths_np = build_paths(enc.parent, enc.max_depth)
        host = solve_drain_np(
            enc.parent,
            enc.level_mask,
            enc.nominal.astype(np.int64, copy=False),
            enc.lending_limit.astype(np.int64, copy=False),
            enc.borrowing_limit.astype(np.int64, copy=False),
            enc.local_usage.astype(np.int64, copy=False),
            plan.queues_np,
            paths_np,
            enc.max_depth,
            plan.max_cycles,
        )
        return _map_drain_result(
            plan,
            host.admitted_k,
            host.admitted_cycle,
            host.cursor,
            host.stuck,
            int(host.cycles),
            plan.queues_np,
            extra_fb_entries=[],
            final_usage=np.asarray(host.local_usage),
        )
    tree, paths, _ = tree_arrays(snapshot)
    queues_np = plan.queues_np
    if mesh is not None:
        import time as _time

        from kueue_tpu.parallel import harness
        from kueue_tpu.parallel.sharded_solver import (
            pad_queue_arrays,
            place_drain_inputs,
        )

        t0p = _time.perf_counter()
        queues_np = pad_queue_arrays(queues_np, mesh.shape["wl"])
        # numpy -> device_put straight onto the shards (one transfer)
        tree, usage_in, queues, paths = place_drain_inputs(
            mesh, tree, snapshot.local_usage, DrainQueues(**queues_np), paths
        )
        harness.note_place_seconds(_time.perf_counter() - t0p)
        harness.note_bucket(
            "drain_kernel",
            (
                queues_np["cells"].shape, plan.n_segments, plan.n_steps,
                plan.max_cycles, "fair" if fair_sharing else "plain",
            ),
            mesh,
        )
    else:
        usage_in = jnp.asarray(snapshot.local_usage)
        queues = DrainQueues(**{k: jnp.asarray(v) for k, v in queues_np.items()})

    if fair_sharing:
        from kueue_tpu.features import enabled as _feature_enabled
        from kueue_tpu.ops.drain_kernel import solve_drain_fair_packed_jit

        n_res = len(snapshot.resource_names)
        depth_of, lendable, res_of_fr = _fair_lendable(
            snapshot, np.asarray(paths)
        )
        if mesh is not None:
            from kueue_tpu.parallel.sharded_solver import (
                place_fair_drain_extras,
            )

            depth_in, weight_in, lendable_in, res_in = (
                place_fair_drain_extras(
                    mesh, depth_of, snapshot.weight_milli, lendable,
                    res_of_fr,
                )
            )
        else:
            depth_in = jnp.asarray(depth_of)
            weight_in = jnp.asarray(snapshot.weight_milli)
            lendable_in = jnp.asarray(lendable)
            res_in = jnp.asarray(res_of_fr)
        flat_dev = solve_drain_fair_packed_jit(
            tree,
            usage_in,
            queues,
            paths,
            depth_in,
            weight_in,
            lendable_in,
            res_in,
            n_segments=plan.n_segments,
            n_steps=plan.n_steps,
            max_cycles=plan.max_cycles,
            n_res=n_res,
            prio_tie=bool(_feature_enabled("PrioritySortingWithinCohort")),
        )
    else:
        flat_dev = solve_drain_packed_jit(
            tree,
            usage_in,
            queues,
            paths,
            n_segments=plan.n_segments,
            n_steps=plan.n_steps,
            max_cycles=plan.max_cycles,
        )
    return DrainLaunch(
        plan=plan,
        queues_np=queues_np,
        flat_dev=flat_dev,
        usage_shape=tuple(snapshot.local_usage.shape),
        extra_fb_entries=extra_fb_entries,
        pending=list(pending),
        max_cycles=plan.max_cycles,
    ).fetch()


def _map_drain_result(
    plan: DrainPlan,
    adm_k,
    adm_cycle,
    cursor,
    stuck_q,
    cycles: int,
    queues_np: dict,
    extra_fb_entries: List[Tuple[Workload, str]],
    final_usage: Optional[np.ndarray] = None,
) -> DrainOutcome:
    """Map a plain drain's per-queue result tensors back onto workloads
    — ONE definition shared by the device fetch and the numpy host
    mirror, so the two paths cannot disagree on outcome decoding."""
    # stuck-frozen queues are terminal no-decisions, not truncation
    truncated = bool(np.any((cursor < queues_np["qlen"]) & ~stuck_q))

    lowered = plan.lowered
    admitted: List[Tuple[Workload, str, Dict[str, str], int]] = []
    parked: List[Tuple[Workload, str]] = []
    extra_fallback: List[Tuple[Workload, str]] = []
    undecided: List[Tuple[Workload, str]] = []
    for (qi, pos), i in plan.head_of.items():
        wl = lowered.heads[i]
        cq_name = lowered.cq_names[i]
        kk = int(adm_k[qi, pos, 0])
        if kk >= 0:
            admitted.append(
                (wl, cq_name, _admitted_flavors(lowered, i, adm_k[qi, pos]),
                 int(adm_cycle[qi, pos]))
            )
        elif pos >= int(cursor[qi]):
            # never processed (max_cycles backstop hit): not a decision.
            # Entries of stuck-frozen queues are terminal no-decisions
            # (a rerun cannot resolve them); the rest are undecided and
            # a follow-up chunk from the post-apply state decides them.
            extra_fallback.append((wl, cq_name))
            if not bool(stuck_q[qi]):
                undecided.append((wl, cq_name))
        else:
            parked.append((wl, cq_name))
    admitted.sort(key=lambda t: t[3])
    fb = (
        [(lowered.heads[i], lowered.cq_names[i]) for i in plan.fallback]
        + extra_fb_entries
        + extra_fallback
    )
    return DrainOutcome(
        admitted=admitted, parked=parked, fallback=fb, cycles=cycles,
        truncated=truncated, undecided=undecided, final_usage=final_usage,
    )
