"""Host glue lowering a Snapshot + cycle heads into the batched solver.

This is the boundary between the object-model world (core/) and the
dense-tensor world (ops/assign_kernel.py). It mirrors the candidate
enumeration the host FlavorAssigner performs sequentially — flavor
eligibility (taints, node-selector labels), resume-from-cursor
(LastAssignment), default-fungibility ordering — but emits it as a
padded (W x K x C) tensor batch the TPU consumes in one dispatch.

Heads the dense formulation cannot represent exactly fall back to the
host authority path and are reported in ``Lowered.fallback``:
  - multi-podset workloads (the reference assigns flavors per podset;
    aggregation would force one flavor for all podsets),
  - non-default flavorFungibility (changes the stop rule away from
    "first Fit wins"),
  - candidate fan-out beyond the static K.
This matches the design stance in SURVEY.md §7: the batched solver
resolves the Fit/NoFit majority; preemption-mode nomination stays host.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu.models import ResourceFlavor, Workload
from kueue_tpu.models.cluster_queue import ClusterQueue
from kueue_tpu.models.constants import FlavorFungibilityPolicy
from kueue_tpu.models.resource_flavor import flavor_eligible, group_label_keys
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload_info import effective_podset_count
from kueue_tpu.resources import PODS, FlavorResource
from kueue_tpu.utils.priority import priority_of


@dataclass
class Lowered:
    """Dense batch + bookkeeping to map results back to workloads."""

    cq_row: np.ndarray  # int32[W]
    cells: np.ndarray  # int32[W,K,C]
    qty: np.ndarray  # int64[W,K,C]
    valid: np.ndarray  # bool[W,K]
    priority: np.ndarray  # int64[W]
    timestamp: np.ndarray  # int64[W] (ns)
    no_reclaim: np.ndarray  # bool[W] — reserve capacity when blocked
    # per head: candidate k -> flavor name chosen per resource group
    candidate_flavors: List[List[Dict[str, str]]] = field(default_factory=list)
    # per head: candidate k -> resource -> host-equivalent tried-flavor
    # cursor (LastAssignment idx; -1 when the chosen flavor is the last
    # of its resource group, matching _find_flavor_for_resource)
    candidate_tried: List[List[Dict[str, int]]] = field(default_factory=list)
    heads: List[Workload] = field(default_factory=list)
    cq_names: List[str] = field(default_factory=list)
    fallback: List[int] = field(default_factory=list)  # indices into input heads


def _default_fungibility(cq: ClusterQueue) -> bool:
    ff = cq.flavor_fungibility
    return (
        ff.when_can_borrow == FlavorFungibilityPolicy.BORROW
        and ff.when_can_preempt == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
    )


def lower_heads(
    snapshot: Snapshot,
    heads: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 16,
    timestamp_fn=None,
) -> Lowered:
    """Build the dense head batch; route inexpressible heads to
    ``fallback`` (handled by the host FlavorAssigner)."""
    w = len(heads)
    k, c = max_candidates, max_cells
    out = Lowered(
        cq_row=np.full(w, -1, dtype=np.int32),
        cells=np.full((w, k, c), -1, dtype=np.int32),
        qty=np.zeros((w, k, c), dtype=np.int64),
        valid=np.zeros((w, k), dtype=bool),
        priority=np.zeros(w, dtype=np.int64),
        timestamp=np.zeros(w, dtype=np.int64),
        no_reclaim=np.zeros(w, dtype=bool),
    )

    for i, (wl, cq_name) in enumerate(heads):
        out.heads.append(wl)
        out.cq_names.append(cq_name)
        out.candidate_flavors.append([])
        out.candidate_tried.append([])
        if cq_name not in snapshot.cq_models:
            out.fallback.append(i)
            continue
        cq = snapshot.cq_models[cq_name]
        if len(wl.pod_sets) != 1 or not _default_fungibility(cq):
            out.fallback.append(i)
            continue
        ps = wl.pod_sets[0]
        if ps.topology_request is not None:
            out.fallback.append(i)  # TAS placement stays on the host path
            continue
        count = effective_podset_count(wl, ps)
        requests = {r: v * count for r, v in ps.requests.items()}

        # resource groups touched by this workload, in CQ order
        touched = []
        for rg in cq.resource_groups:
            rg_req = {
                r: requests[r] for r in rg.covered_resources if r in requests
            }
            if PODS in rg.covered_resources:
                rg_req[PODS] = count
            if rg_req:
                touched.append((rg, rg_req))
        covered = {r for rg, _ in touched for r in rg.covered_resources}
        if any(r not in covered for r in requests):
            out.fallback.append(i)  # resource not covered: host reports it
            continue

        # per-RG eligible flavor lists (order preserved, cursor applied)
        state = wl.last_assignment
        gen = snapshot.generations.get(cq_name, 0)
        if state is not None and gen > state.cluster_queue_generation:
            state = None
        per_rg: List[List[Tuple[str, Dict[str, int], int]]] = []
        representable = True
        for rg, rg_req in touched:
            label_keys = group_label_keys(rg.flavors, flavors)
            start = 0
            if state is not None:
                first_res = sorted(rg_req)[0]
                start = state.next_flavor_to_try(0, first_res)
            n_flavors = len(rg.flavors)
            options: List[Tuple[str, Dict[str, int], int]] = []
            for gi in range(start, n_flavors):
                fq = rg.flavors[gi]
                flavor = flavors.get(fq.name)
                if flavor is not None and flavor.topology_name is not None:
                    # TAS flavors (incl. implied TAS on TAS-only CQs)
                    # need topology placement — host path only
                    options = []
                    representable = False
                    break
                if flavor_eligible(flavor, ps, label_keys):
                    # host cursor semantics: a FIT at the group's last
                    # flavor stores -1 (restart from 0 next time)
                    tried = -1 if gi == n_flavors - 1 else gi
                    options.append((fq.name, rg_req, tried))
            if not representable:
                break
            if not options:
                representable = False
                break
            per_rg.append(options)
        if not representable:
            out.fallback.append(i)
            continue

        n_cand = 1
        for options in per_rg:
            n_cand *= len(options)
        n_cells = sum(len(rg_req) for _, rg_req in touched)
        if n_cand > k or n_cells > c:
            out.fallback.append(i)
            continue

        # cartesian product across RGs in reference order (first RG's
        # flavor walk is the outer loop — matches the sequential search
        # trying RG1 flavors fully per RG0 choice)
        combos: List[List[Tuple[str, Dict[str, int], int]]] = [[]]
        for options in per_rg:
            combos = [prev + [opt] for prev in combos for opt in options]

        from kueue_tpu.core.preemption import can_always_reclaim

        out.cq_row[i] = snapshot.row(cq_name)
        out.no_reclaim[i] = not can_always_reclaim(cq)
        out.priority[i] = priority_of(wl, snapshot.priority_classes)
        ts = timestamp_fn(wl) if timestamp_fn else wl.creation_time
        out.timestamp[i] = int(ts * 1e9)
        for ki, combo in enumerate(combos):
            flavor_map: Dict[str, str] = {}
            tried_map: Dict[str, int] = {}
            ci = 0
            ok = True
            for fname, rg_req, tried in combo:
                for r, q in sorted(rg_req.items()):
                    j = snapshot.fr_index.get(FlavorResource(fname, r))
                    if j is None:
                        ok = False
                        break
                    out.cells[i, ki, ci] = j
                    out.qty[i, ki, ci] = q
                    flavor_map[r] = fname
                    tried_map[r] = tried
                    ci += 1
                if not ok:
                    break
            if ok:
                out.valid[i, ki] = True
                out.candidate_flavors[i].append(flavor_map)
                out.candidate_tried[i].append(tried_map)
            else:
                out.cells[i, ki, :] = -1
                out.qty[i, ki, :] = 0
                out.candidate_flavors[i].append({})
                out.candidate_tried[i].append({})
        if not out.valid[i].any():
            out.cq_row[i] = -1
            out.fallback.append(i)
    return out


def tree_arrays(snapshot: Snapshot):
    """(QuotaTree, paths) device inputs from a Snapshot."""
    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.assign_kernel import build_paths
    from kueue_tpu.ops.quota import QuotaTree

    flat = snapshot.flat
    tree = QuotaTree(
        parent=jnp.asarray(flat.parent),
        level_mask=jnp.asarray(flat.level_masks()),
        nominal=jnp.asarray(snapshot.nominal),
        lending_limit=jnp.asarray(snapshot.lending_limit),
        borrowing_limit=jnp.asarray(snapshot.borrowing_limit),
    )
    paths = jnp.asarray(build_paths(flat.parent, flat.max_depth))
    return tree, paths


def _bucket(w: int, minimum: int = 64) -> int:
    """Round the head count up to a power-of-two bucket so the jit
    solver compiles once per bucket, not once per distinct head count
    (workload arrival is continuous; XLA shapes are static)."""
    n = minimum
    while n < w:
        n *= 2
    return n


def dispatch_lowered(
    snapshot: Snapshot,
    lowered: Lowered,
    pad_heads: bool = True,
):
    """Ship an already-lowered batch to the device solver.

    Padding rows (cq_row == -1) are inert in both solver phases, so the
    first ``len(lowered.heads)`` result entries map 1:1 onto the input
    heads.
    """
    import numpy as np

    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.assign_kernel import HeadsBatch, solve_cycle_jit

    w = len(lowered.heads)
    w_pad = _bucket(w) if pad_heads else w
    cq_row, cells, qty = lowered.cq_row, lowered.cells, lowered.qty
    valid, priority = lowered.valid, lowered.priority
    timestamp, no_reclaim = lowered.timestamp, lowered.no_reclaim
    if w_pad > w:
        pad = w_pad - w
        cq_row = np.concatenate([cq_row, np.full(pad, -1, dtype=np.int32)])
        cells = np.concatenate(
            [cells, np.full((pad,) + cells.shape[1:], -1, dtype=np.int32)]
        )
        qty = np.concatenate([qty, np.zeros((pad,) + qty.shape[1:], dtype=np.int64)])
        valid = np.concatenate([valid, np.zeros((pad,) + valid.shape[1:], dtype=bool)])
        priority = np.concatenate([priority, np.zeros(pad, dtype=np.int64)])
        timestamp = np.concatenate([timestamp, np.zeros(pad, dtype=np.int64)])
        no_reclaim = np.concatenate([no_reclaim, np.zeros(pad, dtype=bool)])
    tree, paths = tree_arrays(snapshot)
    batch = HeadsBatch(
        cq_row=jnp.asarray(cq_row),
        cells=jnp.asarray(cells),
        qty=jnp.asarray(qty),
        valid=jnp.asarray(valid),
        priority=jnp.asarray(priority),
        timestamp=jnp.asarray(timestamp),
        no_reclaim=jnp.asarray(no_reclaim),
    )
    return solve_cycle_jit(tree, jnp.asarray(snapshot.local_usage), batch, paths)


def solve_heads(
    snapshot: Snapshot,
    heads: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 16,
    timestamp_fn=None,
    pad_heads: bool = True,
):
    """One-call convenience: lower, dispatch, return (Lowered, SolveResult)."""
    lowered = lower_heads(
        snapshot, heads, flavors, max_candidates, max_cells, timestamp_fn
    )
    return lowered, dispatch_lowered(snapshot, lowered, pad_heads)
