"""Host glue lowering a Snapshot + cycle heads into the batched solver.

This is the boundary between the object-model world (core/) and the
dense-tensor world (ops/assign_kernel.py). It mirrors the candidate
enumeration the host FlavorAssigner performs sequentially — flavor
eligibility (taints, node-selector labels), resume-from-cursor
(LastAssignment), default-fungibility ordering — but emits it as a
padded (W x K x C) tensor batch the TPU consumes in one dispatch.

Heads the dense formulation cannot represent exactly fall back to the
host authority path and are reported in ``Lowered.fallback``:
  - multi-podset workloads (the reference assigns flavors per podset;
    aggregation would force one flavor for all podsets),
  - non-default flavorFungibility (changes the stop rule away from
    "first Fit wins"),
  - candidate fan-out beyond the static K.
This matches the design stance in SURVEY.md §7: the batched solver
resolves the Fit/NoFit majority; preemption-mode nomination stays host.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu.models import ResourceFlavor, Workload
from kueue_tpu.models.cluster_queue import ClusterQueue
from kueue_tpu.models.constants import FlavorFungibilityPolicy
from kueue_tpu.models.resource_flavor import flavor_eligible, group_label_keys
from kueue_tpu.core.snapshot import Snapshot
from kueue_tpu.core.workload_info import effective_podset_count, quota_per_pod
from kueue_tpu.resources import PODS, FlavorResource
from kueue_tpu.utils.priority import priority_of


@dataclass
class Lowered:
    """Dense batch + bookkeeping to map results back to workloads."""

    cq_row: np.ndarray  # int32[W]
    cells: np.ndarray  # int32[W,K,C]
    qty: np.ndarray  # int64[W,K,C]
    valid: np.ndarray  # bool[W,K]
    priority: np.ndarray  # int64[W]
    timestamp: np.ndarray  # int64[W] (ns)
    no_reclaim: np.ndarray  # bool[W] — reserve capacity when blocked
    # int64[W,K] admission-policy candidate scores (kueue_tpu/policy:
    # annotate_lowered compiles them from workload labels); None = the
    # default first-fit policy (pack_heads ships zeros, the kernel's
    # score-argmax then IS the first-fit argmax)
    score: Optional[np.ndarray] = None

    # per head: candidate k -> flavor name chosen per resource group
    candidate_flavors: List[List[Dict[str, str]]] = field(default_factory=list)
    # per head: candidate k -> resource -> host-equivalent tried-flavor
    # cursor (LastAssignment idx; -1 when the chosen flavor is the last
    # of its resource group, matching _find_flavor_for_resource)
    candidate_tried: List[List[Dict[str, int]]] = field(default_factory=list)
    heads: List[Workload] = field(default_factory=list)
    cq_names: List[str] = field(default_factory=list)
    fallback: List[int] = field(default_factory=list)  # indices into input heads
    # per head: number of resource groups its request touches
    n_groups: List[int] = field(default_factory=list)



def _default_fungibility(cq: ClusterQueue) -> bool:
    ff = cq.flavor_fungibility
    return (
        ff.when_can_borrow == FlavorFungibilityPolicy.BORROW
        and ff.when_can_preempt == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
    )


class _Template:
    """Quantity-independent lowering of one (CQ, podset shape, cursor)
    combination — the candidate enumeration is identical for every
    workload sharing it, so bulk lowering (50k-pending drains) builds it
    once and only fills per-workload quantities."""

    __slots__ = (
        "fallback",
        "n_groups",
        "cq_row",
        "no_reclaim",
        "candidates",
        "any_valid",
        "cells_arr",
        "valid_row",
        "qty_sel",
        "cgrp_arr",
        "res_names",
        "flavor_list",
        "tried_list",
        "group_list",
        "group_sizes",
    )

    def __init__(self):
        self.fallback = False
        self.n_groups = 0
        self.cq_row = -1
        self.no_reclaim = False
        # per candidate slot: None (invalid) or
        # (cell_js, cell_resources, flavor_map, tried_map)
        self.candidates: List = []
        self.any_valid = False
        # dense per-template rows shared by every head using it:
        # cells_arr int32[K,C]; valid_row bool[K]; qty_sel int32[K,C]
        # indexes a per-head request vector laid out as res_names + [0]
        self.cells_arr = None
        self.valid_row = None
        self.qty_sel = None
        # int8[K,C]: resource-group index of each candidate cell (-1 pad)
        self.cgrp_arr = None
        self.res_names: Tuple[str, ...] = ()
        self.flavor_list: List[Dict[str, str]] = []
        self.tried_list: List[Dict[str, int]] = []
        # per candidate: tuple per group of (flavor idx in rg.flavors,
        # is-last-flavor flag); empty tuple for invalid candidates
        self.group_list: List[tuple] = []
        # full walk length (len(rg.flavors)) per touched group — sizes
        # the drain's convergent-retry odometer bound
        self.group_sizes: Tuple[int, ...] = ()


def _podset_sig(ps, per_pod) -> tuple:
    sel = tuple(sorted(ps.node_selector.items())) if ps.node_selector else ()
    return (tuple(sorted(per_pod)), sel, tuple(ps.tolerations))


def _build_template(
    snapshot: Snapshot,
    cq,
    cq_name: str,
    ps,
    per_pod: Dict[str, int],  # quota-view requests (overhead+transform)
    starts: Tuple[int, ...],
    flavors: Dict[str, ResourceFlavor],
    k: int,
    c: int,
    allow_tas: bool = False,
) -> _Template:
    t = _Template()

    # resource groups touched by this podset, in CQ order (names only —
    # quantities are per-workload)
    touched: List[Tuple[object, List[str]]] = []
    for rg in cq.resource_groups:
        rg_res = [r for r in sorted(per_pod) if r in rg.covered_resources]
        if PODS in rg.covered_resources:
            rg_res.append(PODS)
        if rg_res:
            touched.append((rg, sorted(rg_res)))
    covered = {r for rg, _ in touched for r in rg.covered_resources}
    if any(r not in covered for r in per_pod):
        t.fallback = True  # resource not covered: host reports it
        return t
    t.n_groups = len(touched)
    t.group_sizes = tuple(len(rg.flavors) for rg, _ in touched)

    per_rg: List[List[Tuple[str, int]]] = []
    for gidx, (rg, rg_res) in enumerate(touched):
        label_keys = group_label_keys(rg.flavors, flavors)
        start = starts[gidx] if gidx < len(starts) else 0
        n_flavors = len(rg.flavors)
        options: List[Tuple[str, int]] = []
        for gi in range(start, n_flavors):
            fq = rg.flavors[gi]
            flavor = flavors.get(fq.name)
            if (
                not allow_tas
                and flavor is not None
                and flavor.topology_name is not None
            ):
                # TAS flavors (incl. implied TAS on TAS-only CQs)
                # need topology placement — host path only, unless the
                # caller is the TAS drain (run_drain_tas), which does
                # the placement in kernel
                t.fallback = True
                return t
            if flavor_eligible(flavor, ps, label_keys):
                # host cursor semantics: a FIT at the group's last
                # flavor stores -1 (restart from 0 next time)
                last = gi == n_flavors - 1
                tried = -1 if last else gi
                options.append((fq.name, tried, gi, last))
        if not options:
            t.fallback = True
            return t
        per_rg.append(options)

    n_cand = 1
    for options in per_rg:
        n_cand *= len(options)
    n_cells = sum(len(rg_res) for _, rg_res in touched)
    if n_cand > k or n_cells > c:
        t.fallback = True
        return t

    # cartesian product across RGs in reference order (first RG's
    # flavor walk is the outer loop — matches the sequential search
    # trying RG1 flavors fully per RG0 choice)
    combos: List[List[tuple]] = [[]]
    for gidx, options in enumerate(per_rg):
        combos = [
            prev + [(gidx, f, tr, gi, lastf)]
            for prev in combos
            for (f, tr, gi, lastf) in options
        ]

    from kueue_tpu.core.preemption import can_always_reclaim

    t.cq_row = snapshot.row(cq_name)
    t.no_reclaim = not can_always_reclaim(cq)
    for combo in combos:
        cell_js: List[int] = []
        cell_rs: List[str] = []
        cell_gs: List[int] = []
        flavor_map: Dict[str, str] = {}
        tried_map: Dict[str, int] = {}
        gvec: List[tuple] = []
        ok = True
        for gidx, fname, tried, gi, lastf in combo:
            gvec.append((gi, lastf))
            for r in touched[gidx][1]:
                j = snapshot.fr_index.get(FlavorResource(fname, r))
                if j is None:
                    ok = False
                    break
                cell_js.append(j)
                cell_rs.append(r)
                cell_gs.append(gidx)
                flavor_map[r] = fname
                tried_map[r] = tried
            if not ok:
                break
        if ok:
            t.candidates.append(
                (tuple(cell_js), tuple(cell_rs), flavor_map, tried_map, tuple(cell_gs))
            )
            t.group_list.append(tuple(gvec))
            t.any_valid = True
        else:
            t.candidates.append(None)
            t.group_list.append(())
    if not t.any_valid:
        t.fallback = True
        return t

    # dense rows for the vectorized per-head fill
    res_names = tuple(sorted({r for _, rg_res in touched for r in rg_res}))
    r_idx = {r: x for x, r in enumerate(res_names)}
    t.res_names = res_names
    t.cells_arr = np.full((k, c), -1, dtype=np.int32)
    t.valid_row = np.zeros(k, dtype=bool)
    # unused cell slots select the trailing 0 of the request vector
    t.qty_sel = np.full((k, c), len(res_names), dtype=np.int32)
    t.cgrp_arr = np.full((k, c), -1, dtype=np.int8)
    for ki, cand in enumerate(t.candidates):
        if cand is None:
            t.flavor_list.append({})
            t.tried_list.append({})
            continue
        cell_js, cell_rs, flavor_map, tried_map, cell_gs = cand
        for ci, (j, r, cg) in enumerate(zip(cell_js, cell_rs, cell_gs)):
            t.cells_arr[ki, ci] = j
            t.qty_sel[ki, ci] = r_idx[r]
            t.cgrp_arr[ki, ci] = cg
        t.valid_row[ki] = True
        t.flavor_list.append(flavor_map)
        t.tried_list.append(tried_map)
    return t


def _resolve_starts(cq, per_pod, state, ps_idx: int) -> Tuple[int, ...]:
    """Per-resource-group cursor starts from a workload's
    LastAssignment (AssignmentState.next_flavor_to_try) — ONE
    definition shared by the cycle and drain lowerings."""
    if state is None:
        return ()
    starts_l = []
    for rg in cq.resource_groups:
        rg_res = [r for r in sorted(per_pod) if r in rg.covered_resources]
        if PODS in rg.covered_resources:
            rg_res.append(PODS)
        if rg_res:
            starts_l.append(state.next_flavor_to_try(ps_idx, sorted(rg_res)[0]))
    return tuple(starts_l)


def lower_heads(
    snapshot: Snapshot,
    heads: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 16,
    timestamp_fn=None,
    transform=None,  # ResourceTransformConfig for the quota view
) -> Lowered:
    """Build the dense head batch for the INTERACTIVE cycle path; route
    inexpressible heads to ``fallback`` (handled by the host
    FlavorAssigner). The drain lowers via lower_heads_multi, which also
    carries multi-podset, fungibility-policy and cursor-vector inputs.

    Candidate enumeration is memoized per (CQ, podset shape, cursor):
    a bulk backlog over 1k CQs lowers in O(templates + heads), not
    O(heads x flavors)."""
    w = len(heads)
    k, c = max_candidates, max_cells
    out = Lowered(
        cq_row=np.full(w, -1, dtype=np.int32),
        cells=np.full((w, k, c), -1, dtype=np.int32),
        qty=np.zeros((w, k, c), dtype=np.int64),
        valid=np.zeros((w, k), dtype=bool),
        priority=np.zeros(w, dtype=np.int64),
        timestamp=np.zeros(w, dtype=np.int64),
        no_reclaim=np.zeros(w, dtype=bool),
    )
    templates: Dict[tuple, _Template] = {}
    # template key -> (template, head indexes, per-head (per_pod, count))
    groups: Dict[tuple, tuple] = {}

    for i, (wl, cq_name) in enumerate(heads):
        out.heads.append(wl)
        out.cq_names.append(cq_name)
        out.candidate_flavors.append([])
        out.candidate_tried.append([])
        out.n_groups.append(0)
        if cq_name not in snapshot.cq_models:
            out.fallback.append(i)
            continue
        cq = snapshot.cq_models[cq_name]
        if len(wl.pod_sets) != 1 or not _default_fungibility(cq):
            out.fallback.append(i)
            continue
        ps = wl.pod_sets[0]
        if ps.topology_request is not None:
            out.fallback.append(i)  # TAS placement stays on the host path
            continue
        per_pod = quota_per_pod(ps, transform)

        # per-RG cursor starts (LastAssignment resume)
        state = wl.last_assignment
        gen = snapshot.generations.get(cq_name, 0)
        if state is not None and gen > state.cluster_queue_generation:
            state = None
        starts = _resolve_starts(cq, per_pod, state, 0)

        key = (cq_name, _podset_sig(ps, per_pod), starts)
        t = templates.get(key)
        if t is None:
            t = _build_template(
                snapshot, cq, cq_name, ps, per_pod, starts, flavors, k, c
            )
            templates[key] = t
        out.n_groups[i] = t.n_groups
        if t.fallback:
            out.fallback.append(i)
            continue

        count = effective_podset_count(wl, ps)

        out.no_reclaim[i] = t.no_reclaim
        out.priority[i] = priority_of(wl, snapshot.priority_classes)
        ts = timestamp_fn(wl) if timestamp_fn else wl.creation_time
        out.timestamp[i] = int(ts * 1e9)
        # shared read-only maps (one list per template, not per head)
        out.candidate_flavors[i] = t.flavor_list
        out.candidate_tried[i] = t.tried_list
        # defer the array fills: heads sharing a template batch into ONE
        # numpy op per field instead of four small ops per head (the
        # per-head fills dominated bulk-drain lowering wall time)
        group = groups.get(key)
        if group is None:
            group = groups[key] = (t, [], [])
        group[1].append(i)
        group[2].append((per_pod, count))

    for t, idxs, pcs in groups.values():
        ii = np.asarray(idxs, dtype=np.intp)
        out.cq_row[ii] = t.cq_row
        out.cells[ii] = t.cells_arr
        out.valid[ii] = t.valid_row
        # request matrix: rows = heads in this group, cols = the
        # template's resource order (+1 zero column for padding cells)
        rmat = np.zeros((len(ii), len(t.res_names) + 1), dtype=np.int64)
        for x, r in enumerate(t.res_names):
            if r == PODS:
                rmat[:, x] = [count for (_, count) in pcs]
            else:
                rmat[:, x] = [pp.get(r, 0) * count for (pp, count) in pcs]
        out.qty[ii] = rmat[:, t.qty_sel]
    return out


def tree_arrays(snapshot: Snapshot):
    """(QuotaTree, paths, roots) device inputs from a Snapshot, via the
    shared snapshot->array codec (core/encode.py) — the ONE encoding
    the cycle dispatch, the drain and the planner all consume."""
    from kueue_tpu.core.encode import device_arrays, encode_snapshot

    return device_arrays(encode_snapshot(snapshot))


class ResidentCycleState:
    """Device-resident quota tensors for the interactive cycle path.

    The interactive scheduler's device dispatch used to re-ship the
    whole quota tree + usage matrix every cycle; on a remote-attached
    TPU each transfer pays tunnel latency, which dominated the ~140 ms
    interactive round trip and pushed the auto-gate's crossover to
    large head counts. The tree changes rarely (quota/config edits) and
    usage changes touch a few ClusterQueue rows per cycle
    (admissions/evictions/finishes), so both stay RESIDENT on the
    device between cycles: per cycle the host compares the fresh
    snapshot against its copy of the device content and ships only the
    changed usage rows (scatter with a donated buffer — no device-side
    copy), re-uploading everything only when the structure fingerprint
    (row order, cell universe, quota values, cohort edges) changes.
    The heads batch still ships per cycle: it IS the cycle's input.
    """

    def __init__(self):
        self._names = None
        self._parent = None
        self._quota_key = None  # (nominal, lending, borrowing) copies
        self._tree = None
        self._paths = None
        self._roots = None
        self._usage = None  # device [N, FR]
        self._usage_host = None  # numpy mirror of the device content
        # telemetry (BENCH notes / debugging)
        self.full_uploads = 0
        self.delta_cycles = 0
        self.delta_rows = 0

    def _structure_matches(self, snapshot: Snapshot) -> bool:
        import numpy as np

        if self._names != tuple(snapshot.flat.cq_names):
            return False
        if self._usage_host is None or (
            self._usage_host.shape != snapshot.local_usage.shape
        ):
            return False
        if not np.array_equal(self._parent, snapshot.flat.parent):
            return False
        nom, lend, bor = self._quota_key
        return (
            np.array_equal(nom, snapshot.nominal)
            and np.array_equal(lend, snapshot.lending_limit)
            and np.array_equal(bor, snapshot.borrowing_limit)
        )

    def refresh(self, snapshot: Snapshot):
        """(tree, paths, roots, usage_dev) with minimal transfer."""
        import numpy as np

        from kueue_tpu._jax import jnp

        if not self._structure_matches(snapshot):
            self._tree, self._paths, self._roots = tree_arrays(snapshot)
            self._usage = jnp.asarray(snapshot.local_usage)
            self._usage_host = snapshot.local_usage.copy()
            self._names = tuple(snapshot.flat.cq_names)
            self._parent = np.array(snapshot.flat.parent, copy=True)
            self._quota_key = (
                snapshot.nominal.copy(),
                snapshot.lending_limit.copy(),
                snapshot.borrowing_limit.copy(),
            )
            self.full_uploads += 1
            return self._tree, self._paths, self._roots, self._usage

        new = snapshot.local_usage
        changed = (new != self._usage_host).any(axis=1)
        idx = np.flatnonzero(changed)
        if idx.size:
            if idx.size > max(16, new.shape[0] // 4):
                # bulk change: a fresh upload beats a huge scatter
                self._usage = jnp.asarray(new)
            else:
                # bucket the delta width (pad by repeating the first
                # changed row — idempotent under .set) so the scatter
                # jit compiles once per bucket, not once per distinct
                # changed-row count
                n = _bucket(int(idx.size), minimum=4)
                idx_p = np.concatenate(
                    [idx, np.full(n - idx.size, idx[0], dtype=idx.dtype)]
                ).astype(np.int32)
                rows_p = new[idx_p]
                self._usage = _scatter_rows_jit()(
                    self._usage, jnp.asarray(idx_p), jnp.asarray(rows_p)
                )
            self._usage_host = new.copy()
            self.delta_rows += int(idx.size)
        self.delta_cycles += 1
        return self._tree, self._paths, self._roots, self._usage


def _scatter_rows(usage, idx, rows):
    return usage.at[idx].set(rows)


_SCATTER_JIT = None


def _scatter_rows_jit():
    """Lazy jit (module stays importable without configuring JAX);
    donating the resident buffer updates it in place on device."""
    global _SCATTER_JIT
    if _SCATTER_JIT is None:
        from kueue_tpu._jax import jax

        _SCATTER_JIT = jax.jit(_scatter_rows, donate_argnums=(0,))
    return _SCATTER_JIT


def _bucket(w: int, minimum: int = 64) -> int:
    """Round the head count up to a power-of-two bucket so the jit
    solver compiles once per bucket, not once per distinct head count
    (workload arrival is continuous; XLA shapes are static)."""
    n = minimum
    while n < w:
        n *= 2
    return n


def pack_heads(lowered: Lowered, roots, w_pad: int):
    """Pad a lowered head batch to ``w_pad`` rows and derive the
    segmented phase-2 schedule inputs. Shared by the cycle dispatch and
    the planner's scenario batch so the two cannot disagree on padding
    or segment compaction. Returns numpy
    (HeadsBatch, seg_id, n_segments, n_steps)."""
    import numpy as np

    from kueue_tpu.ops.assign_kernel import HeadsBatch

    w = len(lowered.heads)
    cq_row, cells, qty = lowered.cq_row, lowered.cells, lowered.qty
    valid, priority = lowered.valid, lowered.priority
    timestamp, no_reclaim = lowered.timestamp, lowered.no_reclaim
    # policy score tensor: always shipped as a real array (zeros = the
    # default first-fit policy) so every consumer — mesh placement,
    # the planner's vmapped sweep, the host mirror — sees one pytree
    score = lowered.score
    if score is None:
        score = np.zeros(valid.shape, dtype=np.int64)
    if w_pad > w:
        pad = w_pad - w
        cq_row = np.concatenate([cq_row, np.full(pad, -1, dtype=np.int32)])
        cells = np.concatenate(
            [cells, np.full((pad,) + cells.shape[1:], -1, dtype=np.int32)]
        )
        qty = np.concatenate([qty, np.zeros((pad,) + qty.shape[1:], dtype=np.int64)])
        valid = np.concatenate([valid, np.zeros((pad,) + valid.shape[1:], dtype=bool)])
        priority = np.concatenate([priority, np.zeros(pad, dtype=np.int64)])
        timestamp = np.concatenate([timestamp, np.zeros(pad, dtype=np.int64)])
        no_reclaim = np.concatenate([no_reclaim, np.zeros(pad, dtype=bool)])
        score = np.concatenate(
            [score, np.zeros((pad,) + score.shape[1:], dtype=np.int64)]
        )
    batch_np = HeadsBatch(
        cq_row=cq_row, cells=cells, qty=qty, valid=valid,
        priority=priority, timestamp=timestamp, no_reclaim=no_reclaim,
        score=score,
    )
    # compact segment ids: one per LIVE root cohort; the max head count
    # within one root bounds phase-2's sequential depth
    seg_id = np.full(w_pad, -1, dtype=np.int32)
    live_mask = cq_row >= 0
    if live_mask.any():
        uniq, inv = np.unique(roots[cq_row[live_mask]], return_inverse=True)
        seg_id[live_mask] = inv.astype(np.int32)
        n_segments = _bucket(len(uniq), minimum=8)
        n_steps = _bucket(int(np.bincount(inv).max()), minimum=8)
    else:
        n_segments = n_steps = 8
    return batch_np, seg_id, n_segments, n_steps


def dispatch_lowered(
    snapshot: Snapshot,
    lowered: Lowered,
    pad_heads: bool = True,
    mesh=None,  # jax.sharding.Mesh: shard heads along "wl"
    resident: Optional[ResidentCycleState] = None,
):
    """Ship an already-lowered batch to the segmented device solver.

    Padding rows (cq_row == -1) are inert in both solver phases, so the
    first ``len(lowered.heads)`` result entries map 1:1 onto the input
    heads. The phase-2 step bound is the max head count in any root
    cohort (independent roots resolve in parallel), bucketed so the jit
    caches per bucket.

    With ``resident`` (single-device interactive path) the quota tree,
    paths and usage matrix stay device-resident between cycles and the
    host ships only changed usage rows — the heads batch is the only
    per-cycle payload besides the deltas.

    Returns a HOST-side SolveResult (numpy arrays, usage omitted):
    all per-head outputs come back in one packed fetch, because every
    device->host retrieval pays a full round trip on remote-attached
    TPUs and the scheduler reads several fields per cycle.
    """
    import numpy as np

    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.assign_kernel import (
        HeadsBatch,
        SolveResult,
        solve_cycle_segmented_packed_jit,
    )

    w = len(lowered.heads)
    w_pad = _bucket(w) if pad_heads else w
    if mesh is not None:
        # W must divide the mesh's wl axis (uneven device_put shards
        # are rejected); power-of-two buckets already do for
        # power-of-two meshes, this covers the rest
        from kueue_tpu.parallel.sharded_solver import pad_w_multiple

        w_pad = pad_w_multiple(w_pad, mesh.shape["wl"])
    usage_resident = None
    if resident is not None and mesh is None:
        tree, paths, roots, usage_resident = resident.refresh(snapshot)
    else:
        tree, paths, roots = tree_arrays(snapshot)
    batch_np, seg_id, n_segments, n_steps = pack_heads(lowered, roots, w_pad)
    if mesh is not None:
        # numpy -> device_put straight onto the shards (one transfer,
        # no staging of the full batch on a single device)
        from kueue_tpu.parallel.sharded_solver import place_cycle_inputs

        tree, usage_in, batch, paths, seg_in = place_cycle_inputs(
            mesh, tree, snapshot.local_usage, batch_np, paths, seg_id
        )
    else:
        batch = HeadsBatch(*(jnp.asarray(x) for x in batch_np))
        usage_in = (
            usage_resident
            if usage_resident is not None
            else jnp.asarray(snapshot.local_usage)
        )
        seg_in = jnp.asarray(seg_id)
    packed = np.asarray(
        solve_cycle_segmented_packed_jit(
            tree,
            usage_in,
            batch,
            paths,
            seg_in,
            n_segments=n_segments,
            n_steps=n_steps,
        )
    )  # ONE device->host round trip for the whole cycle outcome
    return SolveResult(
        chosen=packed[0].astype(np.int32),
        admitted=packed[1].astype(bool),
        borrows=packed[2].astype(bool),
        reserved=packed[3].astype(bool),
        usage=None,
        order=packed[4].astype(np.int32),
    )


def solve_heads(
    snapshot: Snapshot,
    heads: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 16,
    timestamp_fn=None,
    pad_heads: bool = True,
):
    """One-call convenience: lower, dispatch, return (Lowered, SolveResult)."""
    lowered = lower_heads(
        snapshot, heads, flavors, max_candidates, max_cells, timestamp_fn
    )
    return lowered, dispatch_lowered(snapshot, lowered, pad_heads)


@dataclass
class MultiLowered:
    """Dense multi-podset head batch for the drain: the single-podset
    layout with an extra P axis (podsets padded to a common max). A
    workload's podsets nominate SEQUENTIALLY in the kernel — the
    host couples them only through assignment_usage at shared
    (flavor, resource) cells, so each podset keeps its own candidate
    template, cursor vector, and walk."""

    cq_row: np.ndarray  # int32[W]
    n_podsets: np.ndarray  # int32[W]
    cells: np.ndarray  # int32[W,P,K,C]
    qty: np.ndarray  # int64[W,P,K,C]
    valid: np.ndarray  # bool[W,P,K]
    cgrp: np.ndarray  # int8[W,P,K,C]
    priority: np.ndarray  # int64[W]
    timestamp: np.ndarray  # int64[W]
    no_reclaim: np.ndarray  # bool[W]
    ffb: np.ndarray  # bool[W]
    ffp: np.ndarray  # bool[W]
    # int64[W,P,K] admission-policy candidate scores (kueue_tpu/policy:
    # annotate_multi); None = default first-fit (plan_drain ships zeros)
    score: Optional[np.ndarray] = None
    # per head per podset: candidate k -> maps (template-shared lists)
    candidate_flavors: List[List[list]] = field(default_factory=list)
    candidate_groups: List[List[list]] = field(default_factory=list)
    heads: List[Workload] = field(default_factory=list)
    cq_names: List[str] = field(default_factory=list)
    fallback: List[int] = field(default_factory=list)
    n_groups: List[int] = field(default_factory=list)  # max over podsets
    # per head: number of distinct joint cursor states its podsets' walk
    # odometer can take — prod over podsets of prod over groups of
    # (walk length + 1); a CONVERGENT PendingFlavors retry sequence
    # cannot exceed it, so it is the sound stuck-detection budget
    walk_states: List[int] = field(default_factory=list)


def lower_heads_multi(
    snapshot: Snapshot,
    heads: Sequence[Tuple[Workload, str]],
    flavors: Dict[str, ResourceFlavor],
    max_candidates: int = 8,
    max_cells: int = 16,
    max_podsets: int = 4,
    timestamp_fn=None,
    transform=None,
    any_fungibility: bool = True,
    allow_tas: bool = False,
) -> MultiLowered:
    """lower_heads generalized over podsets (drain path).

    Cursor starts resolve per (podset index, resource) from the
    workload's LastAssignment, exactly like the host's
    AssignmentState.next_flavor_to_try."""
    w = len(heads)
    k, c = max_candidates, max_cells
    # size the podset axis to what the batch actually needs: the
    # common all-single-podset backlog must not pay 4x the memory and
    # memset of a padded axis
    pmax = max(
        [1]
        + [
            len(wl.pod_sets)
            for wl, cqn in heads
            if len(wl.pod_sets) <= max_podsets
            and cqn in snapshot.cq_models
        ]
    )
    out = MultiLowered(
        cq_row=np.full(w, -1, dtype=np.int32),
        n_podsets=np.zeros(w, dtype=np.int32),
        cells=np.full((w, pmax, k, c), -1, dtype=np.int32),
        qty=np.zeros((w, pmax, k, c), dtype=np.int64),
        valid=np.zeros((w, pmax, k), dtype=bool),
        cgrp=np.full((w, pmax, k, c), -1, dtype=np.int8),
        priority=np.zeros(w, dtype=np.int64),
        timestamp=np.zeros(w, dtype=np.int64),
        no_reclaim=np.zeros(w, dtype=bool),
        ffb=np.ones(w, dtype=bool),
        ffp=np.zeros(w, dtype=bool),
    )
    templates: Dict[tuple, _Template] = {}
    groups: Dict[tuple, tuple] = {}  # (key, p) -> (t, idxs, pcs)

    for i, (wl, cq_name) in enumerate(heads):
        out.heads.append(wl)
        out.cq_names.append(cq_name)
        # per-podset maps appended as podsets lower (indexed p <
        # n_podsets only; fallback heads keep empty lists)
        flav_i: list = []
        grp_i: list = []
        out.candidate_flavors.append(flav_i)
        out.candidate_groups.append(grp_i)
        out.n_groups.append(0)
        out.walk_states.append(1)
        if cq_name not in snapshot.cq_models:
            out.fallback.append(i)
            continue
        cq = snapshot.cq_models[cq_name]
        if len(wl.pod_sets) > max_podsets or (
            not any_fungibility and not _default_fungibility(cq)
        ):
            out.fallback.append(i)
            continue
        ff = cq.flavor_fungibility
        out.ffb[i] = ff.when_can_borrow == FlavorFungibilityPolicy.BORROW
        out.ffp[i] = ff.when_can_preempt == FlavorFungibilityPolicy.PREEMPT

        state = wl.last_assignment
        gen = snapshot.generations.get(cq_name, 0)
        if state is not None and gen > state.cluster_queue_generation:
            state = None

        # fast path: the overwhelmingly common single-podset head skips
        # the per-podset list plumbing below (bulk-drain lowering cost)
        if len(wl.pod_sets) == 1:
            ps = wl.pod_sets[0]
            if ps.topology_request is not None and not allow_tas:
                out.fallback.append(i)
                continue
            per_pod = quota_per_pod(ps, transform)
            starts = _resolve_starts(cq, per_pod, state, 0)
            key = (cq_name, _podset_sig(ps, per_pod), starts)
            t = templates.get(key)
            if t is None:
                t = _build_template(
                    snapshot, cq, cq_name, ps, per_pod, starts, flavors, k, c,
                    allow_tas=allow_tas,
                )
                templates[key] = t
            if t.fallback:
                out.fallback.append(i)
                continue
            out.cq_row[i] = t.cq_row
            out.n_podsets[i] = 1
            out.no_reclaim[i] = t.no_reclaim
            out.priority[i] = priority_of(wl, snapshot.priority_classes)
            ts = timestamp_fn(wl) if timestamp_fn else wl.creation_time
            out.timestamp[i] = int(ts * 1e9)
            out.n_groups[i] = t.n_groups
            ws = 1
            for n_g in t.group_sizes:
                ws *= n_g + 1
            out.walk_states[i] = ws
            flav_i.append(t.flavor_list)
            grp_i.append(t.group_list)
            group = groups.get((key, 0))
            if group is None:
                group = groups[(key, 0)] = (t, [], [])
            group[1].append(i)
            group[2].append((per_pod, effective_podset_count(wl, ps)))
            continue

        bad = False
        head_templates = []
        for ps_idx, ps in enumerate(wl.pod_sets):
            if ps.topology_request is not None and not allow_tas:
                bad = True  # TAS placement stays on the host path
                break
            per_pod = quota_per_pod(ps, transform)
            starts = _resolve_starts(cq, per_pod, state, ps_idx)
            key = (cq_name, _podset_sig(ps, per_pod), starts)
            t = templates.get(key)
            if t is None:
                t = _build_template(
                    snapshot, cq, cq_name, ps, per_pod, starts, flavors, k, c
                )
                templates[key] = t
            if t.fallback:
                bad = True
                break
            head_templates.append((key, t, ps, per_pod))
        if bad:
            out.fallback.append(i)
            continue

        out.cq_row[i] = head_templates[0][1].cq_row
        out.n_podsets[i] = len(wl.pod_sets)
        out.no_reclaim[i] = head_templates[0][1].no_reclaim
        out.priority[i] = priority_of(wl, snapshot.priority_classes)
        ts = timestamp_fn(wl) if timestamp_fn else wl.creation_time
        out.timestamp[i] = int(ts * 1e9)
        out.n_groups[i] = max(t.n_groups for _, t, _, _ in head_templates)
        ws = 1
        for _, t, _, _ in head_templates:
            for n_g in t.group_sizes:
                ws *= n_g + 1
        out.walk_states[i] = ws
        for p, (key, t, ps, per_pod) in enumerate(head_templates):
            flav_i.append(t.flavor_list)
            grp_i.append(t.group_list)
            count = effective_podset_count(wl, ps)
            group = groups.get((key, p))
            if group is None:
                group = groups[(key, p)] = (t, [], [])
            group[1].append(i)
            group[2].append((per_pod, count))

    for (key, p), (t, idxs, pcs) in groups.items():
        ii = np.asarray(idxs, dtype=np.intp)
        out.cells[ii, p] = t.cells_arr
        out.valid[ii, p] = t.valid_row
        out.cgrp[ii, p] = t.cgrp_arr
        rmat = np.zeros((len(ii), len(t.res_names) + 1), dtype=np.int64)
        for x, r in enumerate(t.res_names):
            if r == PODS:
                rmat[:, x] = [count for (_, count) in pcs]
            else:
                rmat[:, x] = [pp.get(r, 0) * count for (pp, count) in pcs]
        out.qty[ii, p] = rmat[:, t.qty_sel]
    return out
