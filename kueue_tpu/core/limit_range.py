"""Resource adjustment pipeline — LimitRange, RuntimeClass overhead,
limits-as-requests, and validation.

Behavioral port of pkg/workload/resources.go (AdjustResources /
ValidateResources / ValidateLimitRange) and pkg/util/limitrange
(Summarize + ValidatePodSpec). The granularity differs by design:
this framework's PodSet carries one per-pod request vector rather
than a pod template with containers, so Container-type LimitRange
defaults/bounds apply to the pod-level vector (a PodSet is a set of
homogeneous single-container-equivalent pods); Pod-type bounds apply to the
same vector plus overhead.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

from kueue_tpu.models import Workload
from kueue_tpu.resources import Requests, requests_from_spec

LIMIT_TYPE_CONTAINER = "Container"
LIMIT_TYPE_POD = "Pod"

REQUESTS_MUST_NOT_EXCEED_LIMITS = "requests must not exceed its limits"
ABOVE_MAX = "requests must not be above the limitRange max"
BELOW_MIN = "requests must not be below the limitRange min"


@dataclass
class LimitRangeItem:
    """One spec.limits entry (corev1.LimitRangeItem)."""

    type: str = LIMIT_TYPE_CONTAINER
    max: Requests = field(default_factory=dict)
    min: Requests = field(default_factory=dict)
    default: Requests = field(default_factory=dict)  # default limits
    default_request: Requests = field(default_factory=dict)

    @staticmethod
    def build(type=LIMIT_TYPE_CONTAINER, max=None, min=None, default=None,
              default_request=None) -> "LimitRangeItem":
        return LimitRangeItem(
            type=type,
            max=requests_from_spec(max or {}),
            min=requests_from_spec(min or {}),
            default=requests_from_spec(default or {}),
            default_request=requests_from_spec(default_request or {}),
        )


@dataclass
class LimitRange:
    """Namespaced LimitRange object."""

    namespace: str
    name: str
    items: List[LimitRangeItem] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class RuntimeClass:
    """node.k8s.io RuntimeClass: name + pod-fixed overhead."""

    name: str
    overhead: Requests = field(default_factory=dict)

    @staticmethod
    def build(name: str, overhead=None) -> "RuntimeClass":
        return RuntimeClass(name=name, overhead=requests_from_spec(overhead or {}))


def _merge_keep_first(dst: Requests, src: Requests) -> Requests:
    """resource.MergeResourceListKeepFirst."""
    out = dict(dst)
    for k, v in src.items():
        out.setdefault(k, v)
    return out


def _merge_keep_min(dst: Requests, src: Requests) -> Requests:
    out = dict(dst)
    for k, v in src.items():
        out[k] = min(out[k], v) if k in out else v
    return out


def _merge_keep_max(dst: Requests, src: Requests) -> Requests:
    out = dict(dst)
    for k, v in src.items():
        out[k] = max(out[k], v) if k in out else v
    return out


def summarize(ranges: Iterable[LimitRange]) -> Dict[str, LimitRangeItem]:
    """limitrange.Summarize: fold every item into one per-type summary
    (max keep-min, min keep-max, defaults keep-first)."""
    out: Dict[str, LimitRangeItem] = {}
    for lr in ranges:
        for item in lr.items:
            s = out.setdefault(item.type, LimitRangeItem(type=item.type))
            s.max = _merge_keep_min(s.max, item.max)
            s.min = _merge_keep_max(s.min, item.min)
            s.default = _merge_keep_first(s.default, item.default)
            s.default_request = _merge_keep_first(
                s.default_request, item.default_request
            )
    return out


def adjust_workload_resources(
    wl: Workload,
    limit_ranges: Iterable[LimitRange] = (),
    runtime_classes: Optional[Dict[str, RuntimeClass]] = None,
) -> None:
    """workload.AdjustResources: mutate the spec in place —

    1. RuntimeClass overhead: fill podSet.overhead from the class when
       runtimeClassName is set and overhead is empty (handlePodOverhead);
    2. LimitRange Container defaults: default missing limits/requests
       (handlePodLimitRange);
    3. limits as missing requests (handleLimitsToRequests).
    """
    summary = summarize(lr for lr in limit_ranges if lr.namespace == wl.namespace)
    container = summary.get(LIMIT_TYPE_CONTAINER)
    for ps in wl.pod_sets:
        if ps.runtime_class_name and not ps.overhead and runtime_classes:
            rc = runtime_classes.get(ps.runtime_class_name)
            if rc is not None:
                ps.overhead = dict(rc.overhead)
        if container is not None:
            ps.limits = _merge_keep_first(ps.limits, container.default)
            ps.requests = _merge_keep_first(
                ps.requests, container.default_request
            )
        ps.requests = _merge_keep_first(ps.requests, ps.limits)


def _greater_keys(a: Requests, b: Requests) -> List[str]:
    """resource.GetGreaterKeys: keys present in both where a > b."""
    return sorted(k for k, v in a.items() if k in b and v > b[k])


def validate_resources(wl: Workload) -> List[str]:
    """workload.ValidateResources: requests <= limits."""
    errs: List[str] = []
    for i, ps in enumerate(wl.pod_sets):
        over = _greater_keys(ps.requests, ps.limits)
        if over:
            errs.append(
                f"spec.podSets[{i}]: {over}: {REQUESTS_MUST_NOT_EXCEED_LIMITS}"
            )
    return errs


def validate_limit_range(
    wl: Workload, limit_ranges: Iterable[LimitRange]
) -> List[str]:
    """workload.ValidateLimitRange via Summary.ValidatePodSpec: the
    per-pod vector must sit within Container bounds; the vector plus
    overhead within Pod bounds."""
    summary = summarize(lr for lr in limit_ranges if lr.namespace == wl.namespace)
    errs: List[str] = []
    container = summary.get(LIMIT_TYPE_CONTAINER)
    pod = summary.get(LIMIT_TYPE_POD)
    for i, ps in enumerate(wl.pod_sets):
        path = f"spec.podSets[{i}]"
        if container is not None:
            c_min = _merge_keep_min(ps.requests, ps.limits)
            c_max = _merge_keep_max(ps.requests, ps.limits)
            over = _greater_keys(c_max, container.max)
            if over:
                errs.append(f"{path}: {over}: {ABOVE_MAX}")
            under = _greater_keys(container.min, c_min)
            if under:
                errs.append(f"{path}: {under}: {BELOW_MIN}")
        if pod is not None:
            total = dict(ps.requests)
            for k, v in ps.overhead.items():
                total[k] = total.get(k, 0) + v
            over = _greater_keys(total, pod.max)
            if over:
                errs.append(f"{path}: {over}: {ABOVE_MAX}")
            under = _greater_keys(pod.min, total)
            if under:
                errs.append(f"{path}: {under}: {BELOW_MIN}")
    return errs
