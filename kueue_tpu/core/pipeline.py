"""Double-buffered admission drain — speculation mechanics + stats.

The bulk drain used to be strictly serial per round: encode -> device
solve -> fetch -> host apply (journal append, runtime mutation,
audit/event emission), with the device idle during the apply and the
host idle during the solve. The pipelined loop
(controllers/cluster.ClusterRuntime._pipelined_bulk_drain) overlaps
them: while the host applies round *t*, round *t+1*'s encode + device
solve is already in flight against a SPECULATIVE snapshot — the
kernel-reported final leaf usage of round *t* substituted into round
*t*'s snapshot — over the exact backlog round *t* left undecided.

Correctness never rests on the speculation. At commit time the
speculative inputs are compared against the REAL post-apply state
(``drain_inputs_match`` + ``pending_matches`` below); only on bitwise
agreement is the prefetched result trusted, otherwise it is discarded
(``kueue_pipeline_prefetch_discards_total``) and the round re-solves
from the real snapshot. Drain rounds touch disjoint head prefixes, so
the common case commits. Nothing about a prefetch is journaled or
applied before its commit check passes, which keeps the PR-4/PR-5
crash-consistency story intact — the fault points
``cycle.prefetch_launched`` and ``cycle.commit_pre_apply``
(testing/faults.py) mark the two new windows and the chaos suite in
tests/test_pipeline.py proves a crash in either never ships a stale
decision.

The megaloop (ops/megaloop_kernel, ``--megaloop``) reuses this
module's entire contract one level up: a fused launch computes up to K
rounds per dispatch and the host validates each round of the batched
log with the SAME ``drain_inputs_match`` + ``pending_matches`` check
before applying it — ``MegaloopStats`` below is its accounting twin.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class PipelineStats:
    """Observable pipeline accounting (the ``kueue_pipeline_*`` metric
    source and the dashboard badge detail).

    Written by the drain thread mid-round while the server's request
    threads render ``to_dict`` (dashboard, SIGUSR2 dump) — so every
    mutation goes through a ``note_*`` method under ``_lock`` and
    ``to_dict`` snapshots under the same lock (a dump mid-round must
    never show ``overlapped_apply_s`` from round t with ``apply_s``
    from round t-1). kueuelint's lock-discipline rule enforces the
    annotations below."""

    rounds: int = 0  # guarded by: _lock
    prefetches: int = 0  # guarded by: _lock — speculative launches
    commits: int = 0  # guarded by: _lock — conflict check passed
    discards: int = 0  # guarded by: _lock — invalidated by the apply
    inflight: int = 0  # guarded by: _lock — launches in flight (0|1)
    apply_s: float = 0.0  # guarded by: _lock — host apply wall time
    overlapped_apply_s: float = 0.0  # guarded by: _lock
    solve_s: float = 0.0  # guarded by: _lock — blocked-on-fetch wall
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False,
    )

    # ---- mutation API (the drain thread) ----
    def note_solve(self, seconds: float) -> None:
        with self._lock:
            self.solve_s += seconds

    def note_prefetch(self) -> None:
        with self._lock:
            self.prefetches += 1

    def note_apply(self, seconds: float, overlapped: bool) -> None:
        """One applied round: ``overlapped`` when a speculative solve
        was in flight during the apply."""
        with self._lock:
            self.rounds += 1
            self.apply_s += seconds
            if overlapped:
                self.overlapped_apply_s += seconds

    def note_commit(self) -> None:
        with self._lock:
            self.commits += 1

    def note_discard(self) -> None:
        with self._lock:
            self.discards += 1

    def set_inflight(self, n: int) -> None:
        with self._lock:
            self.inflight = n

    # ---- read API (request threads) ----
    def _overlap_ratio_locked(self) -> float:
        return (
            self.overlapped_apply_s / self.apply_s if self.apply_s > 0 else 0.0
        )

    @property
    def overlap_ratio(self) -> float:
        """Fraction of host apply time that ran with a device solve in
        flight — 1.0 means every apply was fully double-buffered."""
        with self._lock:
            return self._overlap_ratio_locked()

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "rounds": self.rounds,
                "prefetches": self.prefetches,
                "commits": self.commits,
                "discards": self.discards,
                "inflight": self.inflight,
                "overlapRatio": round(self._overlap_ratio_locked(), 4),
                "applyMs": round(self.apply_s * 1e3, 3),
                "overlappedApplyMs": round(
                    self.overlapped_apply_s * 1e3, 3
                ),
                "solveMs": round(self.solve_s * 1e3, 3),
            }


@dataclass
class MegaloopStats:
    """Observable megaloop accounting (the ``kueue_megaloop_*`` metric
    source, the dashboard badge and the SIGUSR2 section).

    Same threading contract as PipelineStats: the drain thread mutates
    mid-batch while request threads render ``to_dict`` — every write
    goes through a ``note_*`` method under ``_lock`` and ``to_dict``
    snapshots under the same lock (kueuelint lock-discipline)."""

    launches: int = 0  # guarded by: _lock — fused dispatches
    rounds: int = 0  # guarded by: _lock — rounds committed (applied)
    device_rounds: int = 0  # guarded by: _lock — rounds the device computed
    truncations: int = 0  # guarded by: _lock — batches cut by a conflict miss
    exhausted: int = 0  # guarded by: _lock — full-K batches with work left
    last_k: int = 0  # guarded by: _lock — rounds-per-launch of the last launch
    last_rounds: int = 0  # guarded by: _lock — rounds the last launch shipped
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False,
    )

    # ---- mutation API (the drain thread) ----
    def note_launch(self, k: int, device_rounds: int) -> None:
        with self._lock:
            self.launches += 1
            self.last_k = k
            self.device_rounds += device_rounds

    def note_committed(self, rounds: int) -> None:
        with self._lock:
            self.rounds += rounds
            self.last_rounds = rounds

    def note_truncation(self) -> None:
        with self._lock:
            self.truncations += 1

    def note_exhausted(self) -> None:
        with self._lock:
            self.exhausted += 1

    # ---- read API (request threads) ----
    def _rounds_per_launch_locked(self) -> float:
        return self.rounds / self.launches if self.launches else 0.0

    @property
    def rounds_per_launch(self) -> float:
        """Committed drain rounds amortized per fused dispatch — the
        megaloop's whole point; 1.0 means the fusion buys nothing."""
        with self._lock:
            return self._rounds_per_launch_locked()

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "launches": self.launches,
                "rounds": self.rounds,
                "deviceRounds": self.device_rounds,
                "truncations": self.truncations,
                "exhausted": self.exhausted,
                "lastK": self.last_k,
                "lastRounds": self.last_rounds,
                "roundsPerLaunch": round(
                    self._rounds_per_launch_locked(), 4
                ),
            }


def speculative_snapshot(snapshot, final_usage: np.ndarray):
    """Round t's snapshot with the kernel-reported final leaf usage
    substituted — the predicted post-apply state round t+1 solves
    against.

    Shallow copy: quota arrays, hierarchy and models are shared (the
    apply never mutates them — it mutates the CACHE, and the next real
    snapshot is taken fresh); only ``local_usage`` is replaced and the
    usage-derived caches dropped so nothing stale leaks through."""
    spec = copy.copy(snapshot)
    spec.local_usage = np.asarray(final_usage, dtype=np.int64).copy()
    spec._usage_cache = None
    spec._avail_cache = None
    spec._drs_cache = None
    spec._tree_usage = None
    spec._usage_version = snapshot._usage_version + 1
    return spec


def drain_inputs_match(spec_snapshot, real_snapshot) -> bool:
    """The commit-time conflict check over everything the plain drain
    kernel reads: hierarchy identity, quota tensors and leaf usage.
    Cheap — a handful of array equality scans — and SOUND: if it
    passes, the speculative launch solved byte-identical inputs to the
    launch a serial loop would have made from ``real_snapshot``."""
    a, b = spec_snapshot, real_snapshot
    if a.flat.cq_names != b.flat.cq_names:
        return False
    if a.fr_list != b.fr_list or a.inactive_cqs != b.inactive_cqs:
        return False
    return (
        np.array_equal(a.flat.parent, b.flat.parent)
        and np.array_equal(a.nominal, b.nominal)
        and np.array_equal(a.lending_limit, b.lending_limit)
        and np.array_equal(a.borrowing_limit, b.borrowing_limit)
        and np.array_equal(a.local_usage, b.local_usage)
    )


def pending_matches(
    speculated: Sequence[Tuple[object, str]],
    actual: Sequence[Tuple[object, str]],
) -> bool:
    """Does the real post-apply backlog equal the one the prefetch was
    planned over? Order matters WITHIN a ClusterQueue (heap order feeds
    the queue tensors positionally) but not across CQs (plan_drain
    re-buckets per CQ)."""
    if len(speculated) != len(actual):
        return False

    def per_cq(items):
        by: Dict[str, List[str]] = {}
        for wl, cq in items:
            by.setdefault(cq, []).append(wl.key)
        return by

    return per_cq(speculated) == per_cq(actual)


def outcome_signature(outcome) -> dict:
    """Decision fingerprint of a DrainOutcome for the sampled
    prefetch-divergence check (guard): everything that feeds the apply,
    nothing incidental."""
    def _fmap(flavors):
        # single-podset {res: flavor} or multi-podset {ps: {res: flavor}}
        return tuple(
            sorted(
                (k, tuple(sorted(v.items())) if isinstance(v, dict) else v)
                for k, v in flavors.items()
            )
        )

    return {
        "admitted": sorted(
            (wl.key, cq, _fmap(flavors), cycle)
            for wl, cq, flavors, cycle in outcome.admitted
        ),
        "parked": sorted((wl.key, cq) for wl, cq in outcome.parked),
        "fallback": sorted((wl.key, cq) for wl, cq in outcome.fallback),
        "cycles": outcome.cycles,
        "truncated": outcome.truncated,
    }
