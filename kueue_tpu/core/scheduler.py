"""The admission scheduler — one cycle of the hot path.

Behavioral equivalent of ``pkg/scheduler/scheduler.go``: pop the head of
every ClusterQueue, snapshot the cache, nominate (validate + flavor
assignment + preemption target search + partial-admission reduction),
order entries (non-borrowing first, then priority, then FIFO — or the
fair-sharing tournament), then admit one-by-one with usage re-checks so
parallel nominations can't double-book quota; leftovers are requeued
with the right reason and a Pending status.

The flavor assignment and quota math run over the dense Snapshot; the
batched solver (ops/assign_kernel.py) accelerates nomination for large
head counts while this driver remains the decision authority.
"""

from __future__ import annotations

import functools
import time as _time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kueue_tpu.models import Workload
from kueue_tpu.models.constants import (
    InadmissibleReason,
    WorkloadConditionType,
    classify_inadmissible_message,
)
from kueue_tpu.core.audit import DecisionAuditLog, DecisionRecord
from kueue_tpu.core.guard import QuarantineList, SolverGuard, bisect_poison
from kueue_tpu.core.flavor_assigner import (
    AssignmentResult,
    FlavorAssigner,
    Mode,
    find_max_counts,
    normalize_reasons,
)
from kueue_tpu.core.queue_manager import QueueManager, RequeueReason, queue_order_timestamp
from kueue_tpu.core.snapshot import Snapshot, WorkloadSnapshot, take_snapshot
from kueue_tpu.utils.clock import Clock
from kueue_tpu.utils.priority import priority_of


class EntryStatus(str, Enum):
    NOT_NOMINATED = ""
    NOMINATED = "nominated"
    SKIPPED = "skipped"
    ASSUMED = "assumed"


@dataclass
class PreemptionTarget:
    workload: WorkloadSnapshot
    reason: str = "InClusterQueue"


@dataclass
class Entry:
    workload: Workload
    cq_name: str
    assignment: Optional[AssignmentResult] = None
    status: EntryStatus = EntryStatus.NOT_NOMINATED
    inadmissible_msg: str = ""
    requeue_reason: RequeueReason = RequeueReason.GENERIC
    preemption_targets: List[PreemptionTarget] = field(default_factory=list)
    # decision-attribution breadcrumbs for the audit trail: which engine
    # nominated this entry and which ran its victim search
    nominated_via: str = "host"
    victim_search: str = ""


class Preemptor:
    """Interface the scheduler drives; ops implementation in
    core/preemption.py (classic + fair sharing)."""

    def get_targets(
        self, wl: Workload, cq_name: str, assignment: AssignmentResult, snapshot: Snapshot
    ) -> List[PreemptionTarget]:
        return []

    def issue_preemptions(
        self, preemptor: Workload, targets: List[PreemptionTarget]
    ) -> int:
        return 0

    def is_reclaim_possible(
        self, snapshot: Snapshot, cq_name: str, wl: Workload, fr, quantity: int
    ) -> bool:
        return False


class _LatencyEstimate:
    """Windowed-min device-latency estimate with skip-erosion re-probe.

    The min over the last ``window`` measurements discards the one-time
    XLA-compile cost in the first sample, yet still RISES within
    ``window`` dispatches when the device genuinely slows down (a plain
    running min can only fall, so once skip-erosion pushed it below the
    true dispatch round trip the gate would lock onto the slower device
    path forever). Erosion accumulates on skipped cycles to force an
    eventual re-probe and resets on the next real measurement, so a
    re-probe that measures slow re-disables the device."""

    def __init__(self, window: int = 5, erosion_rate: float = 0.995):
        self._samples: deque = deque(maxlen=window)
        self._erosion_rate = erosion_rate
        self._erosion = 1.0

    @property
    def value(self) -> Optional[float]:
        if not self._samples:
            return None
        return min(self._samples) * self._erosion

    def observe(self, dt: float) -> None:
        self._samples.append(dt)
        self._erosion = 1.0

    def erode(self) -> None:
        self._erosion *= self._erosion_rate


@dataclass
class CycleTrace:
    """Per-cycle phase attribution — the pprof/log-attribution analog
    (reference: schedulingCycle counter + verbose snapshot/attempt
    dumps, pkg/scheduler/logging.go; the scalability harness' CPU
    profiles). Kept in Scheduler.last_traces (ring buffer), observed
    into the phase-duration histogram by the runtime, dumped by the
    debugger and served at /debug/cycles."""

    cycle: int = 0
    heads: int = 0
    admitted: int = 0
    preempting: int = 0
    resolution: str = "host"
    total_s: float = 0.0
    # phase -> seconds: snapshot / nominate / admit on the cycle path,
    # snapshot / classify / solve / apply on the bulk-drain path
    spans: Dict[str, float] = field(default_factory=dict)
    # device vs host attribution: time spent inside device dispatches
    # (assign/victim kernels, the drain solve) vs everything else
    device_s: float = 0.0
    host_s: float = 0.0
    # mesh annotation: "off" single-device, else the active mesh shape
    # ("wl=8", "wl=4,fr=2") the drain solves sharded over
    mesh: str = "off"
    # cycle span-tree id (kueue_tpu/tracing): the phase timings above
    # are lowered into real parent/child spans under this trace, served
    # at /debug/traces/<id> and referenced by decision records
    trace_id: str = ""

    def to_dict(self) -> dict:
        out = {
            "cycle": self.cycle,
            "heads": self.heads,
            "admitted": self.admitted,
            "preempting": self.preempting,
            "resolution": self.resolution,
            "totalMs": round(self.total_s * 1e3, 3),
            "deviceMs": round(self.device_s * 1e3, 3),
            "hostMs": round(self.host_s * 1e3, 3),
            "mesh": self.mesh,
            "spansMs": {k: round(v * 1e3, 3) for k, v in self.spans.items()},
        }
        if self.trace_id:
            out["traceId"] = self.trace_id
        return out


@dataclass
class CycleResult:
    admitted: List[Entry] = field(default_factory=list)
    preempting: List[Entry] = field(default_factory=list)
    requeued: List[Entry] = field(default_factory=list)
    skipped_preemptions: Dict[str, int] = field(default_factory=dict)
    # which conflict-resolution path ran: "device" (TPU phase-2 scan)
    # or "host" (sequential admit loop)
    resolution: str = "host"


@dataclass
class DevicePlan:
    """Device phase-2 outcome for a pure cycle: the admitted flags and
    entry order computed by ops/assign_kernel.solve_cycle, replayed by
    the host for bookkeeping only (no quota re-checks). ``via`` records
    which engine actually solved it — "device", or "host-mirror" when
    the guard routed the batch to the numpy twin (circuit open /
    forced host mode / divergence quarantine)."""

    entries: List[Entry]
    admitted: "np.ndarray"  # bool[W]
    order: "np.ndarray"  # int32[>=W], device entry order
    via: str = "device"


class Scheduler:
    def __init__(
        self,
        queues: QueueManager,
        cache: Cache,
        clock: Clock,
        preemptor: Optional[Preemptor] = None,
        fair_sharing: bool = False,
        partial_admission: bool = True,
        apply_admission: Optional[Callable[[Workload], bool]] = None,
        wait_for_pods_ready_block: bool = False,
        tas_check=None,
        tas_assign=None,
        tas_fits=None,
        events: Optional[Callable[[str, Workload, str], None]] = None,
        limit_range_validate: Optional[Callable[[Workload], Optional[str]]] = None,
        use_solver: Optional[bool] = None,
        solver_threshold: int = 16,
        use_preempt_solver: Optional[bool] = None,
        preempt_solver_threshold: int = 4,
        transform_config=None,  # ResourceTransformConfig (quota view)
        audit: Optional[DecisionAuditLog] = None,
        guard: Optional[SolverGuard] = None,
        quarantine: Optional[QuarantineList] = None,
        tracer=None,  # tracing.Tracer; None = a private always-on one
        policy=None,  # kueue_tpu/policy AdmissionPolicy; None/first-fit
        #               = score-free nomination (bit-for-bit pre-policy)
    ):
        self.queues = queues
        self.cache = cache
        # One authoritative priority-class store: heap head ordering
        # (queues) and entry ordering / snapshots (cache) must resolve
        # priorities identically.
        if queues.priority_classes is not cache.priority_classes:
            cache.priority_classes.update(queues.priority_classes)
            queues.priority_classes = cache.priority_classes
        self.clock = clock
        self.preemptor = preemptor or Preemptor()
        self.fair_sharing = fair_sharing
        self.partial_admission = partial_admission
        # durable-write hook; returning False simulates API failure
        self.apply_admission = apply_admission or (lambda wl: True)
        self.wait_for_pods_ready_block = wait_for_pods_ready_block
        self.tas_check = tas_check
        self.tas_assign = tas_assign
        self.tas_fits = tas_fits
        self.events = events or (lambda kind, wl, msg: None)
        self.limit_range_validate = limit_range_validate
        # Batched TPU solver as the production nomination path: None =
        # auto (on when the cycle has >= solver_threshold assignable
        # heads), True = always, False = never (host-only oracle path).
        self.use_solver = use_solver
        self.solver_threshold = solver_threshold
        # Batched TPU victim search for preempt-mode heads: None = auto
        # (on when the cycle defers >= preempt_solver_threshold preempt
        # heads), True = always, False = never (host Preemptor loop).
        self.use_preempt_solver = use_preempt_solver
        self.preempt_solver_threshold = preempt_solver_threshold
        self.transform_config = transform_config
        # active admission policy (kueue_tpu/policy). The runtime's
        # set_policy swaps it live; the audit breakdown below explains
        # scored flavor choices per cycle.
        self.policy = policy
        # workload key -> flavor score breakdown of the LAST nomination
        # (cleared per cycle; consumed by _decision_of)
        self._cycle_scores: Dict[str, dict] = {}
        # distributed tracing (kueue_tpu/tracing): cycle span trees are
        # buffered per cycle and flushed atomically with the CycleTrace;
        # a bare Scheduler gets its own tracer, ClusterRuntime shares
        # one across scheduler/audit/guard/journal
        if tracer is None:
            from kueue_tpu.tracing import Tracer

            tracer = Tracer(clock=clock)
        self.tracer = tracer
        # per-workload decision audit trail; both resolution paths (and
        # the runtime's bulk drain) record through the same log
        self.audit = audit if audit is not None else DecisionAuditLog(clock=clock)
        if self.audit.tracer is None:
            self.audit.tracer = self.tracer
        # Resilient solver executor (core/guard.py): exception
        # containment + wall-clock deadline around every device launch,
        # device-path circuit breaker with host-mirror failover, sampled
        # divergence detection. A bare Scheduler gets a hookless guard;
        # ClusterRuntime wires events/metrics/journal into it.
        self.guard = guard if guard is not None else SolverGuard(clock=clock)
        if getattr(self.guard, "tracer", None) is None:
            self.guard.tracer = self.tracer
        # Poison-workload quarantine: shared with the runtime (its TTL
        # sweep and kueuectl surface) when one is attached.
        self.quarantine = (
            quarantine if quarantine is not None else QuarantineList()
        )
        # runtime hooks fired when a workload enters quarantine (journal
        # record + gauge) — None outside a ClusterRuntime
        self.on_quarantine: Optional[Callable[[Workload, str], None]] = None
        self.scheduling_cycle = 0
        # per-cycle phase traces, newest last (ring buffer)
        self.last_traces = deque(maxlen=128)
        # First-class cycle-result hook: every completed cycle (host,
        # device, or runtime bulk drain) is delivered to these
        # callbacks — the public observation surface for preemption
        # reporting and admission spies (no monkeypatching schedule()).
        self.cycle_observers: List[Callable[[CycleResult], None]] = []
        # Latency-aware auto gating. A device dispatch pays a fixed
        # round-trip cost (tens of ms on remote-attached TPUs) that only
        # amortizes once the cycle batches enough heads, so auto mode
        # measures both paths at runtime and routes each cycle to the
        # cheaper one: EMA of the host cost per head, windowed MIN of
        # the observed dispatch wall time (see _LatencyEstimate for why
        # windowed: a running min can only fall, which let skip-erosion
        # permanently lock the gate onto a slow device).
        self._host_assign_ema: Optional[float] = None  # s/head
        self._device_dispatch_est = _LatencyEstimate()  # s/dispatch
        self._host_victim_ema: Optional[float] = None  # s/deferred head
        self._device_victim_est = _LatencyEstimate()  # s/batch
        # device-resident quota tensors for the interactive dispatch:
        # per cycle only changed usage rows + the heads batch transfer
        # (core/solver.ResidentCycleState; VERDICT r4 item 7)
        self._resident_state = None
        # device time accumulated by the CURRENT cycle's dispatches
        # (assign + victim kernels), folded into its CycleTrace
        self._cycle_device_s = 0.0

    # ---- the cycle (scheduler.go:176-310) ----
    def schedule(self) -> CycleResult:
        self.scheduling_cycle += 1
        result = CycleResult()
        trace = CycleTrace(cycle=self.scheduling_cycle)
        self._cycle_device_s = 0.0
        self._cycle_scores.clear()
        t0 = _time.perf_counter()
        self.guard.begin_cycle()

        heads = self.queues.heads()
        trace.heads = len(heads)
        if not heads:
            self.notify_cycle(result)
            return result
        # open the cycle span-tree buffer: mid-cycle spans (divergence
        # checks, fsyncs) and decision records reference its trace id;
        # _finish_trace flushes it atomically, a crashed cycle drops it
        self.tracer.next_cycle(self.scheduling_cycle)
        trace.spans["heads"] = _time.perf_counter() - t0
        try:
            return self._schedule_guarded(heads, result, trace, t0)
        except Exception as exc:  # noqa: BLE001 — the cycle guard's
            # outer containment: an escaped phase exception must cost
            # this cycle's decisions, never the scheduler itself.
            # InjectedCrash (simulated power loss) is a BaseException
            # and passes through untouched.
            return self._contain_cycle_failure(heads, result, trace, t0, exc)

    def _schedule_guarded(self, heads, result, trace, t0) -> CycleResult:
        t1 = _time.perf_counter()
        snapshot = take_snapshot(self.cache)
        trace.spans["snapshot"] = _time.perf_counter() - t1
        self.guard.phase_checkpoint("snapshot")
        t1 = _time.perf_counter()
        entries, device_plan = self._nominate(heads, snapshot)
        trace.spans["nominate"] = _time.perf_counter() - t1
        self.guard.phase_checkpoint(
            "nominate", device_used=self._cycle_device_s > 0
        )
        # crash-consistency fault point: nomination (host walk or device
        # solve) is complete, nothing has been applied or journaled yet
        from kueue_tpu.testing import faults

        faults.fire("cycle.post_solve_pre_apply")
        if device_plan is not None:
            t2 = _time.perf_counter()
            out = self._finalize_device(entries, device_plan, snapshot, result)
            trace.spans["admit"] = _time.perf_counter() - t2
            self.guard.phase_checkpoint(
                "admit", device_used=self._cycle_device_s > 0
            )
            self._finish_trace(trace, out, t0)
            self._audit_cycle(entries, out)
            self.notify_cycle(out)
            return out
        t2 = _time.perf_counter()  # 'admit' includes the entry ordering
        ordered = self._iterate(entries, snapshot)

        preempted_keys: Dict[str, WorkloadSnapshot] = {}
        # Incremental removal: once an entry's targets are accepted they
        # STAY removed from the snapshot (the reference removes + reverts
        # every accumulated target per fits() call — scheduler.go:380-388
        # — which is O(entries x targets) churn; keeping them removed is
        # observationally identical for every later fits() since those
        # remove the same set again). removed_acc reconstructs the
        # pre-removal rows for resourcesToReserve, which the reference
        # evaluates WITH preempted workloads still present. The fair-
        # sharing iterator reads snapshot usage between pops, so that
        # path keeps the reference's remove/revert shape.
        incremental = not self.fair_sharing
        removed_acc: Optional[np.ndarray] = (
            np.zeros_like(snapshot.local_usage) if incremental else None
        )
        for e in ordered:
            if e.assignment is None:
                continue
            mode = e.assignment.representative_mode()
            if mode == Mode.NO_FIT:
                continue

            if mode == Mode.PREEMPT and not e.preemption_targets:
                # Nobody to preempt. Reserve capacity unless reclaim is
                # always possible later (scheduler.go:228-242).
                from kueue_tpu.core.preemption import can_always_reclaim

                cq = snapshot.cq_models[e.cq_name]
                if not can_always_reclaim(cq):
                    snapshot.add_usage(
                        e.cq_name, self._reserve_vector(e, snapshot, removed_acc)
                    )
                continue

            if any(
                t.workload.workload.key in preempted_keys
                for t in e.preemption_targets
            ):
                e.status = EntryStatus.SKIPPED
                e.inadmissible_msg = (
                    "Workload has overlapping preemption targets with another workload"
                )
                result.skipped_preemptions[e.cq_name] = (
                    result.skipped_preemptions.get(e.cq_name, 0) + 1
                )
                continue

            usage_vec = snapshot.vector_of(e.assignment.usage)
            own_removed: List[WorkloadSnapshot] = []
            if incremental:
                for t in e.preemption_targets:
                    ws = snapshot.remove_workload(t.workload.workload.key)
                    if ws is not None:
                        own_removed.append(ws)
                fits_now = snapshot.fits(e.cq_name, usage_vec)
            else:
                fits_now = self._fits_after_removals(
                    snapshot, e, usage_vec, preempted_keys
                )
            if not fits_now:
                for ws in own_removed:
                    snapshot.add_workload(ws)
                e.status = EntryStatus.SKIPPED
                e.inadmissible_msg = (
                    "Workload no longer fits after processing another workload"
                )
                if mode == Mode.PREEMPT:
                    result.skipped_preemptions[e.cq_name] = (
                        result.skipped_preemptions.get(e.cq_name, 0) + 1
                    )
                continue

            # Re-validate topology assignments against in-cycle TAS
            # usage: quota fits() above is blind to domain capacity, but
            # an earlier admission this cycle may have taken the same
            # rack/host (reference Fits' TAS branch,
            # clusterqueue_snapshot.go:135-149).
            if (
                mode == Mode.FIT
                and self.tas_fits is not None
                and any(
                    ps.topology_assignment is not None
                    for ps in e.assignment.pod_sets
                )
            ):
                tas_msg = self.tas_fits(
                    e.workload, e.cq_name, e.assignment, snapshot
                )
                if tas_msg:
                    for ws in own_removed:
                        snapshot.add_workload(ws)
                    e.status = EntryStatus.SKIPPED
                    e.inadmissible_msg = tas_msg
                    continue

            for t in e.preemption_targets:
                preempted_keys[t.workload.workload.key] = t.workload
            if removed_acc is not None:
                for ws in own_removed:
                    removed_acc[ws.cq_row] += ws.usage_vec
            snapshot.add_usage(e.cq_name, usage_vec)

            if mode == Mode.PREEMPT:
                e.workload.last_assignment = None
                n = self.preemptor.issue_preemptions(
                    e.workload, e.preemption_targets, preempting_cq=e.cq_name
                )
                if n:
                    e.inadmissible_msg += (
                        f". Pending the preemption of {n} workload(s)"
                    )
                    e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                result.preempting.append(e)
                continue

            if self.wait_for_pods_ready_block and self.cache.workloads_not_ready:
                e.status = EntryStatus.SKIPPED
                e.inadmissible_msg = (
                    "waiting for all admitted workloads to be in PodsReady condition"
                )
                continue

            e.status = EntryStatus.NOMINATED
            if self._admit(e, snapshot):
                result.admitted.append(e)

        for e in entries:
            if e.status != EntryStatus.ASSUMED:
                self._requeue_and_update(e)
                result.requeued.append(e)
        trace.spans["admit"] = _time.perf_counter() - t2
        self.guard.phase_checkpoint(
            "admit", device_used=self._cycle_device_s > 0
        )
        self._finish_trace(trace, result, t0)
        self._audit_cycle(entries, result)
        self.notify_cycle(result)
        return result

    # ---- cycle guard: containment + poison attribution ----
    def _contain_cycle_failure(
        self, heads, result: CycleResult, trace, t0, exc: Exception
    ) -> CycleResult:
        """An exception escaped a cycle phase. The cycle is charged,
        never the scheduler: admissions that committed before the raise
        stand (they are in the cache and in ``result.admitted``); every
        other popped head is requeued so nothing is stranded. Poison
        attribution runs a side-effect-free nomination probe over the
        heads and bisects to the offender(s); a head that keeps doing
        this crosses the strike threshold and is quarantined."""
        self.guard.note_contained_cycle(exc)
        for wl in bisect_poison(list(heads), self._nomination_probe):
            msg = self._poison_strike(wl, exc)
            wl.set_condition(
                WorkloadConditionType.QUOTA_RESERVED, False,
                reason=classify_inadmissible_message(msg).value,
                message=msg, now=self.clock.now(),
            )
        for wl in heads:
            if wl.key in self.cache.assumed_workloads or self._is_admitted(wl):
                continue
            # FAILED_AFTER_NOMINATION: straight back onto the heap (a
            # GENERIC requeue would park every innocent head in the
            # inadmissible lot with nothing to reactivate it — the
            # contained cycle must cost a retry, not the backlog)
            self.queues.requeue_workload(
                wl, RequeueReason.FAILED_AFTER_NOMINATION
            )
        self._finish_trace(trace, result, t0)
        self.notify_cycle(result)
        return result

    def _nomination_probe(self, subset) -> None:
        """Re-run prevalidation + host flavor assignment for a subset of
        heads against a throwaway snapshot — raises iff the subset
        contains a head whose scheduling raises. Side-effect-free: the
        snapshot is private and the flavor cursors are restored."""
        snap = take_snapshot(self.cache)
        saved = [(wl, wl.last_assignment) for wl in subset]
        try:
            _entries, to_assign = self._prevalidate(list(subset), snap)
            assigner = self._make_assigner(snap)
            for e in to_assign:
                self._host_assign(assigner, e, snap, None)
        finally:
            for wl, la in saved:
                wl.last_assignment = la

    def _poison_strike(self, wl: Workload, exc) -> str:
        """One contained failure attributed to this head: strike it,
        quarantine at the threshold. Returns the cycle's
        inadmissibility message (classifies to SCHEDULING_FAILURE /
        QUARANTINED)."""
        n = self.quarantine.strike(wl.key)
        if n >= self.quarantine.threshold:
            msg = (
                f"The workload is quarantined after {n} scheduling "
                f"failures (last: {exc!r})"
            )
            self._do_quarantine(wl, msg)
            return msg
        return (
            f"Workload raised during scheduling ({exc!r}); strike "
            f"{n}/{self.quarantine.threshold} toward quarantine"
        )

    def _do_quarantine(self, wl: Workload, msg: str) -> None:
        now = self.clock.now()
        self.quarantine.add(wl.key, msg, now)
        wl.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, False,
            reason=InadmissibleReason.QUARANTINED.value,
            message=msg, now=now,
        )
        self.events("WorkloadQuarantined", wl, msg)
        if self.on_quarantine is not None:
            self.on_quarantine(wl, msg)

    def _contain_head_failure(self, e: Entry, exc: Exception) -> None:
        """Per-head exception containment in the nomination loops: the
        head costs itself, not the cycle. Attribution is exact here, so
        no bisection is needed."""
        e.assignment = None
        e.preemption_targets = []
        e.inadmissible_msg = self._poison_strike(e.workload, exc)

    def notify_cycle(self, result: CycleResult) -> None:
        for cb in list(self.cycle_observers):
            cb(result)

    def _finish_trace(self, trace: "CycleTrace", result: CycleResult, t0) -> None:
        trace.total_s = _time.perf_counter() - t0
        trace.admitted = len(result.admitted)
        trace.preempting = len(result.preempting)
        trace.resolution = result.resolution
        trace.device_s = self._cycle_device_s
        trace.host_s = max(trace.total_s - self._cycle_device_s, 0.0)
        # the phase timings above, lowered into a real span tree (one
        # atomic flush — a cycle that never reaches here leaks nothing)
        self.tracer.record_cycle(trace)
        self.last_traces.append(trace)

    # ---- decision audit (core/audit.py) ----
    def _audit_cycle(self, entries: List[Entry], result: CycleResult) -> None:
        if self.audit is None:
            return
        preempting = {id(e) for e in result.preempting}
        for e in entries:
            self.audit.record(
                self._decision_of(e, result.resolution, id(e) in preempting)
            )

    def _decision_of(
        self, e: Entry, resolution: str, is_preempting: bool
    ) -> DecisionRecord:
        """Lower one entry's cycle outcome into a DecisionRecord. Both
        resolution paths funnel through here, so an identical scenario
        attributes identically whether the device scan or the host loop
        decided it."""
        a = e.assignment
        flavors: Dict[str, Dict[str, str]] = {}
        flavor_reasons: Dict[str, List[str]] = {}
        topology: Optional[dict] = None
        borrowing = False
        if a is not None:
            borrowing = a.borrowing
            for ps in a.pod_sets:
                if ps.flavors:
                    flavors[ps.name] = {
                        res: c.name for res, c in sorted(ps.flavors.items())
                    }
                if ps.reasons:
                    flavor_reasons[ps.name] = normalize_reasons(ps.reasons)
                ta = ps.topology_assignment
                if ta is not None:
                    topology = topology or {}
                    topology[ps.name] = {
                        "levels": list(ta.levels),
                        "domains": [
                            {"values": list(d.values), "count": d.count}
                            for d in ta.domains
                        ],
                    }
        preemption: Optional[dict] = None
        if e.preemption_targets:
            preemption = {
                "victims": [
                    {
                        "workload": t.workload.workload.key,
                        "reason": t.reason,
                    }
                    for t in e.preemption_targets
                ],
                "search": e.victim_search or "host",
            }
        elif a is not None and a.representative_mode() == Mode.PREEMPT:
            preemption = {"blocked": "no preemption candidates found"}

        if e.status == EntryStatus.ASSUMED:
            outcome, reason = "Admitted", InadmissibleReason.ADMITTED
        elif is_preempting:
            outcome = "Preempting"
            reason = (
                InadmissibleReason.PENDING_PREEMPTION
                if e.requeue_reason == RequeueReason.PENDING_PREEMPTION
                else InadmissibleReason.PREEMPTING
            )
        elif e.status == EntryStatus.SKIPPED:
            outcome = "Skipped"
            reason = classify_inadmissible_message(e.inadmissible_msg)
        else:
            outcome = "Pending"
            reason = classify_inadmissible_message(e.inadmissible_msg)

        cached = self.cache.cluster_queues.get(e.cq_name)
        cohort = cached.model.cohort or "" if cached is not None else ""
        return DecisionRecord(
            workload=e.workload.key,
            cluster_queue=e.cq_name,
            cycle=self.scheduling_cycle,
            outcome=outcome,
            reason=reason,
            message=e.inadmissible_msg,
            resolution=resolution,
            nominated_via=e.nominated_via,
            borrowing=borrowing,
            cohort=cohort,
            flavors=flavors,
            flavor_reasons=flavor_reasons,
            preemption=preemption,
            topology=topology,
            scores=self._cycle_scores.get(e.workload.key),
        )

    def _record_cycle_scores(self, lowered) -> None:
        """Per-head flavor score breakdown for the audit trail
        (kueue_tpu/policy): score per candidate flavor set, the
        highest-scoring set, and the winning margin — `kueuectl
        explain` renders it so operators see WHY a flavor won. The
        actual assignment (which may differ when the top-scoring
        flavor doesn't fit) rides the record's ``flavors`` field."""
        score = lowered.score
        if score is None:
            return
        fallback = set(lowered.fallback)
        for i, wl in enumerate(lowered.heads):
            if i in fallback:
                continue
            per: Dict[str, int] = {}
            for k, fmap in enumerate(lowered.candidate_flavors[i]):
                if not fmap or k >= lowered.valid.shape[1]:
                    continue
                if not lowered.valid[i, k]:
                    continue
                sig = "+".join(sorted(set(fmap.values())))
                sc = int(score[i, k])
                if sig not in per or sc > per[sig]:
                    per[sig] = sc
            if not per:
                continue
            ranked = sorted(per.items(), key=lambda t: (-t[1], t[0]))
            margin = (
                ranked[0][1] - ranked[1][1] if len(ranked) > 1 else ranked[0][1]
            )
            self._cycle_scores[wl.key] = {
                "policy": self.policy.name,
                "perFlavor": per,
                "winner": ranked[0][0],
                "margin": margin,
            }

    # ---- nomination (scheduler.go:344-378) ----
    def _nominate(
        self, heads: List[Workload], snapshot: Snapshot
    ) -> Tuple[List[Entry], Optional[DevicePlan]]:
        entries, to_assign = self._prevalidate(heads, snapshot)
        if self._solver_enabled(len(to_assign)):
            plan = self._assign_with_solver(to_assign, snapshot)
            return entries, plan
        assigner = self._make_assigner(snapshot)
        deferred: List[Entry] = []
        t_host = _time.perf_counter()
        for e in to_assign:
            try:
                self._host_assign(assigner, e, snapshot, deferred)
            except Exception as exc:  # noqa: BLE001 — per-head guard:
                # the raising head costs itself, never the cycle
                self._contain_head_failure(e, exc)
        if to_assign:
            per_head = (_time.perf_counter() - t_host) / len(to_assign)
            self._host_assign_ema = (
                per_head
                if self._host_assign_ema is None
                else 0.8 * self._host_assign_ema + 0.2 * per_head
            )
        self._resolve_deferred(assigner, deferred, snapshot)
        return entries, None

    # cold-start guesses until the first real measurement lands
    _HOST_ASSIGN_DEFAULT = 1e-4  # s/head, host flavor loop
    _HOST_VICTIM_DEFAULT = 4e-3  # s/head, host victim search

    def _solver_enabled(self, n_assignable: int) -> bool:
        if self.use_solver is False or n_assignable == 0:
            return False
        if self.use_solver is True:
            return True
        if n_assignable < self.solver_threshold:
            return False
        device_est = self._device_dispatch_est.value
        if device_est is None:
            return True  # probe once; the measurement gates later cycles
        host_est = n_assignable * (
            self._host_assign_ema or self._HOST_ASSIGN_DEFAULT
        )
        if host_est >= device_est:
            return True
        self._device_dispatch_est.erode()  # stale-estimate re-probe
        return False

    def _victim_device_worthwhile(self, n_deferred: int) -> bool:
        device_est = self._device_victim_est.value
        if device_est is None:
            return True  # probe once
        host_est = n_deferred * (
            self._host_victim_ema or self._HOST_VICTIM_DEFAULT
        )
        if host_est >= device_est:
            return True
        self._device_victim_est.erode()
        return False

    def _make_assigner(self, snapshot: Snapshot) -> FlavorAssigner:
        return FlavorAssigner(
            snapshot,
            self.cache.flavors,
            enable_fair_sharing=self.fair_sharing,
            reclaim_oracle=functools.partial(self._reclaim_oracle, snapshot),
            tas_check=self.tas_check,
            transform=self.transform_config,
            policy=self.policy,
        )

    def _host_assign(
        self,
        assigner: FlavorAssigner,
        e: Entry,
        snapshot: Snapshot,
        deferred: Optional[List[Entry]] = None,
    ) -> None:
        """Assign flavors; preempt-mode entries are parked in
        ``deferred`` (when given) so the whole cycle's victim searches
        run in ONE batched device dispatch (_resolve_deferred) instead
        of a sequential simulate/undo loop per head. All searches run
        against the cycle-start snapshot either way, so deferral cannot
        change decisions."""
        if deferred is not None and self.use_preempt_solver is not False:
            full = assigner.assign(e.workload, e.cq_name)
            if full.representative_mode() == Mode.PREEMPT:
                e.assignment = full
                deferred.append(e)
                return
            assignment, targets = self._finish_assignment(
                assigner, e.workload, e.cq_name, snapshot, full
            )
        else:
            assignment, targets = self._get_assignments(
                assigner, e.workload, e.cq_name, snapshot
            )
        e.assignment = assignment
        e.preemption_targets = targets
        e.inadmissible_msg = assignment.message()
        e.workload.last_assignment = assignment.last_state

    def _resolve_deferred(
        self, assigner: FlavorAssigner, deferred: List[Entry], snapshot: Snapshot
    ) -> None:
        """Victim search for every deferred preempt-mode entry —
        batched on device above the threshold, host loop otherwise."""
        if not deferred:
            return
        batch_on = self.use_preempt_solver is True or (
            self.use_preempt_solver is None
            and len(deferred) >= self.preempt_solver_threshold
            and self._victim_device_worthwhile(len(deferred))
        )
        t0 = _time.perf_counter()
        if batch_on:
            from kueue_tpu.core.preempt_batch import batched_get_targets

            try:
                all_targets = batched_get_targets(
                    snapshot,
                    [(e.workload, e.cq_name, e.assignment) for e in deferred],
                    self.preemptor,
                )
                dt = _time.perf_counter() - t0
                self._device_victim_est.observe(dt)
                self._cycle_device_s += dt
            except Exception:  # noqa: BLE001 — a failed victim-search
                # kernel degrades to the host loop, never the cycle
                batch_on = False
                t0 = _time.perf_counter()
        if not batch_on:
            all_targets = []
            for e in deferred:
                try:
                    all_targets.append(
                        self.preemptor.get_targets(
                            e.workload, e.cq_name, e.assignment, snapshot
                        )
                    )
                except Exception as exc:  # noqa: BLE001 — per-head guard
                    self._contain_head_failure(e, exc)
                    all_targets.append([])
            per_head = (_time.perf_counter() - t0) / len(deferred)
            self._host_victim_ema = (
                per_head
                if self._host_victim_ema is None
                else 0.8 * self._host_victim_ema + 0.2 * per_head
            )
        for e, targets in zip(deferred, all_targets):
            if e.assignment is None:
                continue  # contained above: strike message already set
            e.victim_search = "device" if batch_on else "host"
            try:
                if targets:
                    e.preemption_targets = targets
                else:
                    e.assignment, e.preemption_targets = self._finish_assignment(
                        assigner, e.workload, e.cq_name, snapshot, e.assignment
                    )
                e.inadmissible_msg = e.assignment.message()
                e.workload.last_assignment = e.assignment.last_state
            except Exception as exc:  # noqa: BLE001 — per-head guard
                self._contain_head_failure(e, exc)

    def _prevalidate(
        self, heads: List[Workload], snapshot: Snapshot
    ) -> Tuple[List[Entry], List[Entry]]:
        """Per-head admission preconditions (scheduler.go:361-369).
        Returns (all entries, the subset needing flavor assignment)."""
        entries: List[Entry] = []
        to_assign: List[Entry] = []
        for wl in heads:
            cq_name = self.queues.cluster_queue_for_workload(wl) or ""
            e = Entry(workload=wl, cq_name=cq_name)
            entries.append(e)
            if wl.key in self.cache.assumed_workloads or self._is_admitted(wl):
                entries.pop()  # already assumed/admitted: drop silently
                continue
            if self.quarantine.active(wl.key, self.clock.now()):
                # sidelined poison head: never nominated until its TTL
                # lapses or an operator clears it (kueuectl quarantine)
                q = self.quarantine.get(wl.key)
                e.inadmissible_msg = (
                    f"The workload is quarantined until t={q.until:.0f}: "
                    f"{q.message}"
                )
                continue
            if not wl.is_active():
                e.inadmissible_msg = "The workload is deactivated"
                continue
            if wl.has_retry_check() or wl.has_rejected_check():
                e.inadmissible_msg = "The workload has failed admission checks"
                continue
            if cq_name in snapshot.inactive_cqs:
                e.inadmissible_msg = f"ClusterQueue {cq_name} is inactive"
                continue
            if cq_name not in snapshot.cq_models:
                e.inadmissible_msg = f"ClusterQueue {cq_name} not found"
                continue
            cq = snapshot.cq_models[cq_name]
            ns_labels = self.queues.namespace_labels(wl.namespace)
            if not cq.selects_namespace(ns_labels):
                e.inadmissible_msg = (
                    "Workload namespace doesn't match ClusterQueue selector"
                )
                e.requeue_reason = RequeueReason.NAMESPACE_MISMATCH
                continue
            if self.limit_range_validate is not None:
                err = self.limit_range_validate(wl)
                if err:
                    e.inadmissible_msg = err
                    continue
            to_assign.append(e)
        return entries, to_assign

    # ---- batched nomination on the device (the production hot path) ----
    def _assign_with_solver(
        self, to_assign: List[Entry], snapshot: Snapshot
    ) -> Optional[DevicePlan]:
        """Nominate every assignable head in one device dispatch
        (ops/assign_kernel.solve_cycle); heads the dense formulation
        can't represent — multi-podset, non-default fungibility, TAS,
        candidate overflow — and heads the kernel classifies non-Fit
        (potential preemption) fall back to the host FlavorAssigner,
        which remains the decision authority for them.

        The launch itself runs under the SolverGuard: a raising/late
        device dispatch (or an open circuit / divergence quarantine)
        resolves the same lowered batch on the numpy host mirror
        instead — per-head host fallback is the last resort when even
        lowering fails.

        Returns a DevicePlan when the whole cycle is resolvable from
        the device phase-2 scan (every host-path entry is NO_FIT with
        no preemption targets, so no usage interleaving outside the
        device model); otherwise None, and the host admit loop runs
        over the device-assigned entries.
        """
        from kueue_tpu.core.solver import dispatch_lowered, lower_heads

        heads = [(e.workload, e.cq_name) for e in to_assign]
        try:
            lowered = lower_heads(
                snapshot,
                heads,
                self.cache.flavors,
                timestamp_fn=lambda wl: queue_order_timestamp(wl, self.queues._ts_policy),
                transform=self.transform_config,
            )
        except Exception as exc:  # noqa: BLE001 — batch-level lowering
            # failure: bisect to the poison head(s), host path for the
            # rest (per-head contained)
            self._bisect_lowering_failure(to_assign, snapshot, exc)
            return None
        if self.policy is not None and not self.policy.is_default:
            # compile the policy's score tensors onto the batch BEFORE
            # the guard sees it: the device kernel and the host mirror
            # both read lowered.score, so divergence checks stay sound
            from kueue_tpu.policy import annotate_lowered

            annotate_lowered(self.policy, lowered, now=self.clock.now())
            self._record_cycle_scores(lowered)
        fallback = set(lowered.fallback)
        if len(fallback) == len(to_assign):
            # nothing representable: skip the device dispatch entirely
            self._host_assign_contained(to_assign, snapshot)
            return None
        if self._resident_state is None:
            from kueue_tpu.core.solver import ResidentCycleState

            self._resident_state = ResidentCycleState()
        outcome = self.guard.solve(
            snapshot,
            lowered,
            dispatch=lambda: dispatch_lowered(
                snapshot, lowered, resident=self._resident_state
            ),
        )
        if outcome.result is None:
            # device failed AND the host mirror raised (a poison head
            # corrupting the batch): per-head host fallback decides
            self._host_assign_contained(to_assign, snapshot)
            return None
        if outcome.device_dt is not None:
            self._device_dispatch_est.observe(outcome.device_dt)
            self._cycle_device_s += outcome.device_dt
        res = outcome.result
        chosen = np.asarray(res.chosen)
        host_idx = [
            i
            for i in range(len(to_assign))
            if i in fallback or chosen[i] < 0
        ]
        if host_idx:
            self._host_assign_contained(
                [to_assign[i] for i in host_idx], snapshot
            )
        host_set = set(host_idx)
        for i, e in enumerate(to_assign):
            if i in host_set:
                continue
            e.nominated_via = outcome.via
            e.assignment = self._assignment_from_device(
                lowered, i, int(chosen[i]), snapshot
            )
            e.workload.last_assignment = e.assignment.last_state

        # Pure cycle: nothing host-side can mutate usage, so the device
        # scan's admitted flags ARE the cycle outcome.
        pure = (
            not self.fair_sharing
            and all(
                to_assign[i].assignment is not None
                and to_assign[i].assignment.representative_mode() == Mode.NO_FIT
                and not to_assign[i].preemption_targets
                for i in host_idx
            )
        )
        if not pure:
            return None
        return DevicePlan(
            entries=to_assign,
            admitted=np.asarray(res.admitted),
            order=np.asarray(res.order),
            via=outcome.via,
        )

    def _host_assign_contained(
        self, entries: List[Entry], snapshot: Snapshot
    ) -> None:
        """Host FlavorAssigner pass with per-head exception containment
        — the guard's last-resort fallback and the device path's
        host-side companion for unrepresentable heads."""
        assigner = self._make_assigner(snapshot)
        deferred: List[Entry] = []
        for e in entries:
            try:
                self._host_assign(assigner, e, snapshot, deferred)
            except Exception as exc:  # noqa: BLE001 — per-head guard
                self._contain_head_failure(e, exc)
        self._resolve_deferred(assigner, deferred, snapshot)

    def _bisect_lowering_failure(
        self, to_assign: List[Entry], snapshot: Snapshot, exc: Exception
    ) -> None:
        """lower_heads raised for the whole batch — attribution needs
        the guard's bisection (the raise names no head). Poison heads
        are struck/quarantined; the rest nominate on the host path."""
        from kueue_tpu.core.solver import lower_heads

        def probe(subset) -> None:
            lower_heads(
                snapshot,
                [(e.workload, e.cq_name) for e in subset],
                self.cache.flavors,
                timestamp_fn=lambda wl: queue_order_timestamp(
                    wl, self.queues._ts_policy
                ),
                transform=self.transform_config,
            )

        poison = bisect_poison(to_assign, probe)
        for e in poison:
            self._contain_head_failure(e, exc)
        poison_ids = {id(e) for e in poison}
        self._host_assign_contained(
            [e for e in to_assign if id(e) not in poison_ids], snapshot
        )

    def _assignment_from_device(
        self,
        lowered,
        i: int,
        k: int,
        snapshot: Snapshot,
    ) -> AssignmentResult:
        """Reconstruct the host-equivalent FIT AssignmentResult from the
        kernel's chosen candidate (single podset, default fungibility —
        lower_heads guarantees these invariants for non-fallback heads)."""
        from kueue_tpu.core.flavor_assigner import (
            AssignmentState,
            FlavorChoice,
            GranularMode,
            PodSetResult,
        )
        from kueue_tpu.core.workload_info import effective_podset_count

        wl = lowered.heads[i]
        cq_name = lowered.cq_names[i]
        ps = wl.pod_sets[0]
        count = effective_podset_count(wl, ps)
        flavor_map = lowered.candidate_flavors[i][k]
        tried_map = lowered.candidate_tried[i][k]
        r = snapshot.row(cq_name)
        psr = PodSetResult(name=ps.name, count=count)
        usage: Dict = {}
        result = AssignmentResult(pod_sets=[psr])
        cells = lowered.cells[i, k]
        qty = lowered.qty[i, k]
        for c in range(cells.shape[0]):
            j = int(cells[c])
            if j < 0:
                continue
            fr = snapshot.fr_list[j]
            q = int(qty[c])
            usage[fr] = usage.get(fr, 0) + q
            # per-resource borrow flag (flavorassigner.go:698): request
            # pushes the CQ above nominal in this cell
            borrow = bool(
                snapshot.local_usage[r, j] + q > snapshot.nominal[r, j]
            ) and snapshot.has_cohort(cq_name)
            if borrow:
                result.borrowing = True
            psr.flavors[fr.resource] = FlavorChoice(
                name=fr.flavor,
                mode=GranularMode.FIT,
                tried_flavor_idx=tried_map.get(fr.resource, -1),
                borrow=borrow,
            )
        result.usage = usage
        result.last_state = AssignmentState(
            last_tried_flavor_idx=[dict(tried_map)],
            cluster_queue_generation=snapshot.generations.get(cq_name, 0),
        )
        return result

    def _finalize_device(
        self,
        entries: List[Entry],
        plan: DevicePlan,
        snapshot: Snapshot,
        result: CycleResult,
    ) -> CycleResult:
        """Replay the device phase-2 outcome: admit flagged entries in
        device order (bookkeeping only — the scan already resolved
        conflicts), skip Fit entries the scan rejected, requeue the
        rest. Mirrors the tail of the host loop (scheduler.go:211-292)
        minus the per-entry quota re-checks."""
        result.resolution = plan.via
        for idx in plan.order:
            if idx >= len(plan.entries):
                continue  # padding rows
            e = plan.entries[int(idx)]
            if e.assignment is None:
                continue
            if e.assignment.representative_mode() != Mode.FIT:
                continue
            if bool(plan.admitted[int(idx)]):
                snapshot.add_usage(
                    e.cq_name, snapshot.vector_of(e.assignment.usage)
                )
                if self.wait_for_pods_ready_block and self.cache.workloads_not_ready:
                    e.status = EntryStatus.SKIPPED
                    e.inadmissible_msg = (
                        "waiting for all admitted workloads to be in PodsReady condition"
                    )
                    continue
                e.status = EntryStatus.NOMINATED
                if self._admit(e, snapshot):
                    result.admitted.append(e)
            else:
                e.status = EntryStatus.SKIPPED
                e.inadmissible_msg = (
                    "Workload no longer fits after processing another workload"
                )
        for e in entries:
            if e.status != EntryStatus.ASSUMED:
                self._requeue_and_update(e)
                result.requeued.append(e)
        return result

    def _is_admitted(self, wl: Workload) -> bool:
        cached = self.cache.cluster_queues.get(
            wl.admission.cluster_queue if wl.admission else ""
        )
        return cached is not None and wl.key in cached.workloads

    def _reclaim_oracle(
        self, snapshot: Snapshot, cq_name: str, wl: Workload, fr, quantity: int
    ) -> bool:
        return self.preemptor.is_reclaim_possible(snapshot, cq_name, wl, fr, quantity)

    # ---- assignment + preemption + partial admission (scheduler.go:423-468) ----
    def _get_assignments(
        self,
        assigner: FlavorAssigner,
        wl: Workload,
        cq_name: str,
        snapshot: Snapshot,
    ) -> Tuple[AssignmentResult, List[PreemptionTarget]]:
        full = assigner.assign(wl, cq_name)
        if full.representative_mode() == Mode.PREEMPT:
            targets = self.preemptor.get_targets(wl, cq_name, full, snapshot)
            if targets:
                return full, targets
        return self._finish_assignment(assigner, wl, cq_name, snapshot, full)

    def _finish_assignment(
        self,
        assigner: FlavorAssigner,
        wl: Workload,
        cq_name: str,
        snapshot: Snapshot,
        full: AssignmentResult,
    ) -> Tuple[AssignmentResult, List[PreemptionTarget]]:
        """Tail of getAssignments once preemption targets are known to
        be absent: TAS attach for Fit, else partial-admission search."""
        if full.representative_mode() == Mode.FIT:
            return self._with_tas(wl, cq_name, full, snapshot), []
        if self.partial_admission and any(
            ps.min_count is not None for ps in wl.pod_sets
        ):
            best: Optional[AssignmentResult] = None

            def try_counts(counts: Sequence[int]) -> AssignmentResult:
                nonlocal best
                a = assigner.assign(wl, cq_name, counts=counts)
                if a.representative_mode() == Mode.FIT:
                    best = a
                return a

            found = find_max_counts(try_counts, wl)
            if found is not None and best is not None:
                return self._with_tas(wl, cq_name, best, snapshot), []
        return full, []

    def _with_tas(
        self, wl: Workload, cq_name: str, assignment: AssignmentResult, snapshot: Snapshot
    ) -> AssignmentResult:
        if self.tas_assign is not None:
            return self.tas_assign(wl, cq_name, assignment, snapshot)
        return assignment

    # ---- ordering (scheduler.go:561-642) ----
    def _iterate(self, entries: List[Entry], snapshot: Snapshot):
        if self.fair_sharing:
            from kueue_tpu.core.fair_sharing_iterator import fair_sharing_iter

            # lazy: each pop re-evaluates DRS against the snapshot as
            # mutated by admissions earlier in this cycle
            return fair_sharing_iter(entries, snapshot, self._fair_tie_key)
        return sorted(entries, key=self._entry_sort_key)

    def _fair_tie_key(self, e: "Entry"):
        """Non-DRS tournament tiebreak (fair_sharing_iterator.go less()):
        priority desc behind PrioritySortingWithinCohort, then FIFO."""
        from kueue_tpu.features import enabled

        parts = []
        if enabled("PrioritySortingWithinCohort"):
            parts.append(-priority_of(e.workload, self.cache.priority_classes))
        parts.append(
            int(queue_order_timestamp(e.workload, self.queues._ts_policy) * 1e9)
        )
        return tuple(parts)

    def _entry_sort_key(self, e: Entry):
        borrows = e.assignment.borrowing if e.assignment else False
        prio = priority_of(e.workload, self.cache.priority_classes)
        # int-ns, matching the heap ranks and the device lexsort key so
        # every ordering surface agrees on near-ties
        ts = int(queue_order_timestamp(e.workload, self.queues._ts_policy) * 1e9)
        return (1 if borrows else 0, -prio, ts)

    # ---- usage re-check (scheduler.go:380-388) ----
    def _fits_after_removals(
        self,
        snapshot: Snapshot,
        e: Entry,
        usage_vec: np.ndarray,
        preempted: Dict[str, WorkloadSnapshot],
    ) -> bool:
        removed: List[WorkloadSnapshot] = []
        for ws in list(preempted.values()):
            if snapshot.remove_workload(ws.workload.key) is not None:
                removed.append(ws)
        for t in e.preemption_targets:
            ws = snapshot.remove_workload(t.workload.workload.key)
            if ws is not None:
                removed.append(ws)
        ok = snapshot.fits(e.cq_name, usage_vec)
        for ws in removed:
            snapshot.add_workload(ws)
        return ok

    # ---- capacity reservation on blocked preemption (scheduler.go:391-416) ----
    def _reserve_vector(
        self,
        e: Entry,
        snapshot: Snapshot,
        removed_acc: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        usage_vec = snapshot.vector_of(e.assignment.usage)
        r = snapshot.row(e.cq_name)
        if e.assignment.representative_mode() != Mode.PREEMPT:
            return usage_vec
        reserved = np.zeros_like(usage_vec)
        from kueue_tpu.ops.quota import NO_LIMIT

        # the reference evaluates reservation with this cycle's
        # preempted workloads still counted in usage; under incremental
        # removal removed_acc restores that view
        local = snapshot.local_usage[r]
        if removed_acc is not None:
            local = local + removed_acc[r]
        for j in range(len(usage_vec)):
            u = int(usage_vec[j])
            if u == 0:
                continue
            if e.assignment.borrowing:
                bl = int(snapshot.borrowing_limit[r, j])
                if bl >= NO_LIMIT:
                    reserved[j] = u
                else:
                    reserved[j] = min(
                        u,
                        int(snapshot.nominal[r, j]) + bl - int(local[j]),
                    )
            else:
                reserved[j] = max(
                    0, min(u, int(snapshot.nominal[r, j]) - int(local[j]))
                )
        return reserved

    # ---- admission (scheduler.go:498-555) ----
    def _admit(self, e: Entry, snapshot: Snapshot) -> bool:
        admission = e.assignment.to_admission(
            e.cq_name, e.workload, transform=self.transform_config
        )
        ok, msg = self.admit_prepared(
            e.workload, e.cq_name, admission, snapshot.cq_models[e.cq_name]
        )
        if ok:
            e.status = EntryStatus.ASSUMED
        else:
            e.inadmissible_msg = msg
            # end-of-cycle loop requeues every non-assumed entry
            e.status = EntryStatus.NOMINATED
        return ok

    def admit_prepared(self, wl: Workload, cq_name: str, admission, cq_model) -> Tuple[bool, str]:
        """Admission tail shared by the cycle loop and the runtime's
        bulk drain: set conditions + check states from a ready Admission
        object, assume in the cache, durable-write. Returns (ok, msg)."""
        now = self.clock.now()
        wl.admission = admission
        wl.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, True, reason="QuotaReserved", now=now
        )
        # initialize admission-check states for checks applying to the
        # assigned flavors (two-phase admission)
        flavors_used = {
            f for psa in admission.pod_set_assignments for f in psa.flavors.values()
        }
        from kueue_tpu.models.admission_check import AdmissionCheckState

        required = self.cache.admission_checks_for_workload(cq_model, flavors_used)
        for name in required:
            if name not in wl.admission_check_states:
                wl.admission_check_states[name] = AdmissionCheckState(name=name)
        if wl.all_checks_ready(required):
            wl.set_condition(
                WorkloadConditionType.ADMITTED, True, reason="Admitted", now=now
            )

        # Stage → commit. The condition writes above are the STAGE; the
        # cache assumption + durable write below are the COMMIT, and
        # any exception inside rolls this head back completely (cache
        # forgotten, conditions reverted) before converting to an
        # ordinary requeue — so a raising durable-write hook mid-apply
        # can never leave cached usage != Σ admitted. InjectedCrash is
        # a BaseException and still models a real process death.
        try:
            if not self.cache.assume_workload(wl):
                msg = "Failed to assume workload"
                self._rollback_admission(wl, msg)
                return False, msg
            # Workload leaves the pending queue: drop the flavor cursor
            # so a later eviction restarts from the first flavor.
            wl.last_assignment = None

            if not self.apply_admission(wl):
                self.cache.forget_workload(wl)
                msg = "Failed to admit workload: durable write failed"
                self._rollback_admission(wl, msg)
                return False, msg
        except Exception as exc:  # noqa: BLE001 — transactional apply
            if wl.key in self.cache.assumed_workloads:
                self.cache.forget_workload(wl)
            msg = f"Failed to admit workload: durable write failed ({exc!r})"
            self._rollback_admission(wl, msg)
            return False, msg
        self.events(
            "QuotaReserved", wl, f"Quota reserved in ClusterQueue {cq_name}"
        )
        if wl.is_admitted:
            self.events("Admitted", wl, f"Admitted by ClusterQueue {cq_name}")
        return True, ""

    def _rollback_admission(self, wl: Workload, msg: str) -> None:
        """Undo the optimistic condition writes of a failed admission
        (reference: UnsetQuotaReservationWithCondition on this path)."""
        wl.admission = None
        now = self.clock.now()
        wl.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, False, reason="Pending",
            message=msg, now=now,
        )
        if wl.conditions.get(WorkloadConditionType.ADMITTED) is not None:
            wl.set_condition(
                WorkloadConditionType.ADMITTED, False, reason="NoReservation", now=now
            )

    # ---- requeue path (scheduler.go:644-665) ----
    def _requeue_and_update(self, e: Entry) -> None:
        if (
            e.status != EntryStatus.NOT_NOMINATED
            and e.requeue_reason == RequeueReason.GENERIC
        ):
            e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
        self.queues.requeue_workload(e.workload, e.requeue_reason)
        if e.status in (EntryStatus.NOT_NOMINATED, EntryStatus.SKIPPED):
            # the structured reason rides on the condition: operators
            # (and the visibility API) read WHY from the reason without
            # parsing the free-form message
            canonical = classify_inadmissible_message(e.inadmissible_msg)
            e.workload.set_condition(
                WorkloadConditionType.QUOTA_RESERVED,
                False,
                reason=(
                    canonical.value
                    if canonical != InadmissibleReason.UNKNOWN
                    else "Pending"
                ),
                message=e.inadmissible_msg,
                now=self.clock.now(),
            )
            self.events("Pending", e.workload, e.inadmissible_msg)
