"""Per-cycle snapshot: cache state flattened into dense tensors.

Equivalent of the reference's ``pkg/cache/snapshot.go`` +
``clusterqueue_snapshot.go``, redesigned struct-of-arrays: instead of a
cloned object forest with simulate/undo closures, the snapshot is a set
of flat arrays (cohort-parent indices, per-node quota cells, a single
mutable [node x flavor-resource] local-usage matrix) over which

- availability queries evaluate the whole forest at once
  (ops/quota_np for host-side loops, ops/quota for the jit solver), and
- preemption simulation is add/subtract on one usage row — no object
  graph mutation, trivially undoable, and directly shippable to the
  TPU solver as one contiguous buffer.

Workload usage vectors are dense int64[FR] rows, so Fits/Simulate*
(clusterqueue_snapshot.go:75-150) become vector compares.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from kueue_tpu.models import ClusterQueue, Workload
from kueue_tpu.models.cluster_queue import ResourceQuota
from kueue_tpu.core.cache import Cache
from kueue_tpu.core.hierarchy import FlatHierarchy
from kueue_tpu.core.workload_info import admission_usage
from kueue_tpu.ops.quota import NO_LIMIT
from kueue_tpu.ops.quota_np import (
    available_all_np,
    dominant_resource_share_np,
    potential_available_all_np,
    subtree_quota_np,
    usage_tree_np,
)
from kueue_tpu.resources import FlavorResource, FlavorResourceQuantities


@dataclass
class WorkloadSnapshot:
    workload: Workload
    cq_name: str
    cq_row: int
    usage_vec: np.ndarray  # int64[FR]
    priority: int
    quota_reserved_time: float


@dataclass
class Snapshot:
    flat: FlatHierarchy
    fr_list: Tuple[FlavorResource, ...]
    fr_index: Dict[FlavorResource, int]
    resource_names: Tuple[str, ...]
    resource_index: np.ndarray  # int32[FR] -> resource id (sorted names)
    # quota arrays [N, FR]
    nominal: np.ndarray
    lending_limit: np.ndarray
    borrowing_limit: np.ndarray
    subtree: np.ndarray
    guaranteed: np.ndarray
    # mutable during the cycle
    local_usage: np.ndarray  # int64[N, FR]; nonzero only on CQ rows
    weight_milli: np.ndarray  # int64[N]
    cq_models: Dict[str, ClusterQueue]
    workloads: Dict[str, WorkloadSnapshot] = field(default_factory=dict)
    # per-CQ workload index (maintained by add/remove_workload) and
    # memoized root/membership lookups — the preemption candidate scan
    # hits these once per head per cycle
    _by_cq: Dict[str, Dict[str, WorkloadSnapshot]] = field(default_factory=dict)
    _roots: Optional[np.ndarray] = None
    _members: Dict[int, Set[str]] = field(default_factory=dict)
    inactive_cqs: Tuple[str, ...] = ()
    # AllocatableResourceGeneration per CQ (invalidates LastAssignment)
    generations: Dict[str, int] = field(default_factory=dict)
    # WorkloadPriorityClass map for consistent priority resolution
    priority_classes: Dict[str, object] = field(default_factory=dict)
    # derived-matrix caches, invalidated by _mutated()
    _usage_version: int = 0
    _usage_cache: Optional[tuple] = None
    _avail_cache: Optional[tuple] = None
    _pa_cache: Optional[np.ndarray] = None
    _drs_cache: Optional[tuple] = None
    # incrementally-maintained tree usage (usage_tree_np semantics):
    # updated along the mutated row's ancestor path in O(depth*FR)
    # instead of re-running the full level-scheduled reduction, so the
    # admit loop's per-entry fits() re-check is a path walk, not a
    # matrix recompute.
    _tree_usage: Optional[np.ndarray] = None
    _paths: Dict[int, List[int]] = field(default_factory=dict)

    # ---- derived state ----
    # The usage/available matrices are O(N*FR) tree reductions queried
    # thousands of times per cycle (per head, per flavor) but mutated
    # only between queries (add/remove usage). A version counter keyed
    # cache collapses the recomputes to one per mutation epoch.
    def _mutated(self) -> None:
        self._usage_version += 1

    def usage(self) -> np.ndarray:
        if self._usage_cache is None or self._usage_cache[0] != self._usage_version:
            self._usage_cache = (
                self._usage_version,
                usage_tree_np(
                    self.flat.parent, self._lm(), self.guaranteed,
                    self.local_usage,
                ),
            )
        return self._usage_cache[1]

    # ---- incremental tree usage + single-row available ----
    def _ensure_tree_usage(self) -> np.ndarray:
        if self._tree_usage is None:
            self._tree_usage = self.usage().copy()
        return self._tree_usage

    def _apply_tree_delta(self, row: int, vec: np.ndarray, sign: int) -> None:
        """Propagate a leaf usage change up the cohort path; exact twin
        of re-running usage_tree_np (child contribution to its parent is
        max(0, usage - guaranteed))."""
        if self._tree_usage is None:
            return
        U, G, parent = self._tree_usage, self.guaranteed, self.flat.parent
        delta = sign * vec
        cur = row
        while True:
            old_excess = np.maximum(0, U[cur] - G[cur])
            U[cur] = U[cur] + delta
            p = int(parent[cur])
            if p < 0:
                break
            delta = np.maximum(0, U[cur] - G[cur]) - old_excess
            if not delta.any():
                break
            cur = p

    def _path_of(self, row: int) -> List[int]:
        path = self._paths.get(row)
        if path is None:
            path = [row] + self.path_to_root(row)
            self._paths[row] = path
        return path

    def available_row(self, row: int) -> np.ndarray:
        """available() for one node via a root->node path walk over the
        incrementally-maintained tree usage — O(depth*FR) instead of the
        O(N*FR) full reduction; parity with available_all_np is asserted
        in tests."""
        U = self._ensure_tree_usage()
        path = self._path_of(row)
        root = path[-1]
        avail = self.subtree[root] - U[root]
        for n in reversed(path[:-1]):
            stored = self.subtree[n] - self.guaranteed[n]
            used = np.maximum(0, U[n] - self.guaranteed[n])
            with_max = stored - used + self.borrowing_limit[n]
            has_borrow = self.borrowing_limit[n] < NO_LIMIT
            clamped = np.where(has_borrow, np.minimum(with_max, avail), avail)
            avail = np.maximum(0, self.guaranteed[n] - U[n]) + clamped
        return avail

    def available(self) -> np.ndarray:
        if self._avail_cache is None or self._avail_cache[0] != self._usage_version:
            self._avail_cache = (
                self._usage_version,
                available_all_np(
                    self.flat.parent, self._lm(), self.subtree,
                    self.guaranteed, self.borrowing_limit, self.usage(),
                ),
            )
        return self._avail_cache[1]

    def potential_available(self) -> np.ndarray:
        if self._pa_cache is None:  # usage-independent: compute once
            self._pa_cache = potential_available_all_np(
                self.flat.parent, self._lm(), self.subtree, self.guaranteed,
                self.borrowing_limit,
            )
        return self._pa_cache

    def _lm(self) -> np.ndarray:
        return self.flat.level_masks()

    def row(self, cq_name: str) -> int:
        return self.flat.index[cq_name]

    # ---- queries (ClusterQueueSnapshot equivalents) ----
    def fits(self, cq_name: str, usage_vec: np.ndarray) -> bool:
        """FitInCohort/Fits: every requested cell within available."""
        avail = self.available_row(self.row(cq_name))
        need = usage_vec > 0
        return bool(np.all(avail[need] >= usage_vec[need]))

    def available_for(self, cq_name: str) -> np.ndarray:
        return self.available_row(self.row(cq_name))

    def borrowing_after(self, cq_name: str, usage_vec: np.ndarray) -> bool:
        """Would admitting usage_vec push the CQ above its nominal
        subtree quota in any cell (i.e. require borrowing)?"""
        r = self.row(cq_name)
        after = self.local_usage[r] + usage_vec
        return bool(np.any(after > self.subtree[r]))

    def is_borrowing(self, cq_name: str) -> bool:
        r = self.row(cq_name)
        return bool(np.any(self.local_usage[r] > self.subtree[r]))

    # ---- simulation (SimulateUsageAddition/Removal, RemoveWorkload) ----
    def add_usage(self, cq_name: str, usage_vec: np.ndarray) -> None:
        row = self.row(cq_name)
        self.local_usage[row] += usage_vec
        self._apply_tree_delta(row, usage_vec, 1)
        self._mutated()

    def remove_usage(self, cq_name: str, usage_vec: np.ndarray) -> None:
        row = self.row(cq_name)
        self.local_usage[row] -= usage_vec
        self._apply_tree_delta(row, usage_vec, -1)
        self._mutated()

    def add_workload(self, ws: WorkloadSnapshot) -> None:
        self.workloads[ws.workload.key] = ws
        self._by_cq.setdefault(ws.cq_name, {})[ws.workload.key] = ws
        self.local_usage[ws.cq_row] += ws.usage_vec
        self._apply_tree_delta(ws.cq_row, ws.usage_vec, 1)
        self._mutated()

    def remove_workload(self, wl_key: str) -> Optional[WorkloadSnapshot]:
        ws = self.workloads.pop(wl_key, None)
        if ws is not None:
            self._by_cq.get(ws.cq_name, {}).pop(wl_key, None)
            self.local_usage[ws.cq_row] -= ws.usage_vec
            self._apply_tree_delta(ws.cq_row, ws.usage_vec, -1)
            self._mutated()
        return ws

    def workloads_in_cq(self, cq_name: str) -> List[WorkloadSnapshot]:
        return list(self._by_cq.get(cq_name, {}).values())

    def workloads_in_cohort_of(self, cq_name: str) -> List[WorkloadSnapshot]:
        members = self.cohort_members(cq_name)
        return [
            ws
            for m in members
            for ws in self._by_cq.get(m, {}).values()
        ]

    def roots(self) -> np.ndarray:
        """int32[N] root node per node, computed once per snapshot."""
        if self._roots is None:
            from kueue_tpu.ops.assign_kernel import build_roots

            self._roots = build_roots(self.flat.parent)
        return self._roots

    def cohort_members(self, cq_name: str) -> Set[str]:
        """All CQ names in the same cohort tree (incl. cq_name)."""
        roots = self.roots()
        me = int(roots[self.row(cq_name)])
        cached = self._members.get(me)
        if cached is None:
            cached = {
                name
                for name in self.flat.cq_names
                if int(roots[self.flat.index[name]]) == me
            }
            self._members[me] = cached
        return cached

    def has_cohort(self, cq_name: str) -> bool:
        return self.flat.parent[self.row(cq_name)] >= 0

    # ---- fair sharing ----
    def dominant_resource_share(
        self, cq_name: str, wl_req: Optional[np.ndarray] = None
    ) -> int:
        n, fr = self.local_usage.shape
        wl = np.zeros((n, fr), dtype=np.int64)
        if wl_req is not None:
            wl[self.row(cq_name)] = wl_req
        dws, _ = dominant_resource_share_np(
            self.flat.parent, self._lm(), self.subtree, self.guaranteed,
            self.borrowing_limit, self.usage(), wl, self.weight_milli,
            self.resource_index, len(self.resource_names),
        )
        return int(dws[self.row(cq_name)])

    def all_node_drs(self) -> np.ndarray:
        """DominantResourceShare of every node (CQs and cohorts) against
        current usage — used by the fair-sharing preemption tournament.
        Version-cached: the tournament asks several times per pick while
        usage only changes between picks."""
        if self._drs_cache is None or self._drs_cache[0] != self._usage_version:
            n, fr = self.local_usage.shape
            dws, _ = dominant_resource_share_np(
                self.flat.parent, self._lm(), self.subtree, self.guaranteed,
                self.borrowing_limit, self.usage(),
                np.zeros((n, fr), dtype=np.int64), self.weight_milli,
                self.resource_index, len(self.resource_names),
            )
            self._drs_cache = (self._usage_version, dws)
        return self._drs_cache[1]

    def path_to_root(self, row: int) -> List[int]:
        """Node rows from `row`'s parent up to (and including) the root."""
        out: List[int] = []
        cur = int(self.flat.parent[row])
        while cur >= 0:
            out.append(cur)
            cur = int(self.flat.parent[cur])
        return out

    def children_of(self, row: int) -> Tuple[List[int], List[int]]:
        """(cq_children, cohort_children) rows of a cohort node."""
        cqs, cohorts = [], []
        n_cq = self.flat.n_cq
        for i, p in enumerate(self.flat.parent):
            if int(p) == row:
                (cqs if i < n_cq else cohorts).append(i)
        return cqs, cohorts

    def vector_of(self, usage: FlavorResourceQuantities) -> np.ndarray:
        vec = np.zeros(len(self.fr_list), dtype=np.int64)
        for fr, qty in usage.items():
            j = self.fr_index.get(fr)
            if j is not None:
                vec[j] += qty
        return vec


def _quota_cells(
    node_quotas: Dict[FlavorResource, ResourceQuota],
    fr_index: Dict[FlavorResource, int],
    nominal: np.ndarray,
    lend: np.ndarray,
    borrow: np.ndarray,
    row: int,
) -> None:
    for fr, q in node_quotas.items():
        j = fr_index[fr]
        nominal[row, j] = q.nominal
        if q.lending_limit is not None:
            lend[row, j] = q.lending_limit
        if q.borrowing_limit is not None:
            borrow[row, j] = q.borrowing_limit


def _collect_quotas(resource_groups) -> Dict[FlavorResource, ResourceQuota]:
    out: Dict[FlavorResource, ResourceQuota] = {}
    for rg in resource_groups:
        for fq in rg.flavors:
            for rname, q in fq.resources.items():
                out[FlavorResource(fq.name, rname)] = q
    return out


def take_snapshot(cache: Cache) -> Snapshot:
    """Flatten the cache into a Snapshot (pkg/cache/snapshot.go:104-158).

    Inactive ClusterQueues (stopped, missing flavors/checks/topologies,
    cyclic cohorts) are excluded and reported, mirroring
    InactiveClusterQueueSets.
    """
    active_names: List[str] = []
    inactive: List[str] = []
    for name in sorted(cache.cluster_queues):
        if cache.cluster_queue_status(name).active:
            active_names.append(name)
        else:
            inactive.append(name)

    flat = cache.forest.flatten(active_names)
    inactive.extend(flat.inactive_cqs)

    # FR universe: every (flavor, resource) cell defined by any active CQ
    # or cohort resource group.
    frs: Set[FlavorResource] = set()
    for name in flat.cq_names:
        frs |= set(_collect_quotas(cache.cluster_queues[name].model.resource_groups))
    for cname in flat.cohort_names:
        cohort = cache.cohorts.get(cname)
        if cohort is not None:
            frs |= set(_collect_quotas(cohort.resource_groups))
    fr_list = tuple(sorted(frs))
    fr_index = {fr: j for j, fr in enumerate(fr_list)}
    resource_names = tuple(sorted({fr.resource for fr in fr_list}))
    rname_index = {r: i for i, r in enumerate(resource_names)}
    resource_index = np.array(
        [rname_index[fr.resource] for fr in fr_list], dtype=np.int32
    )

    n = flat.n_nodes
    nominal = np.zeros((n, len(fr_list)), dtype=np.int64)
    lend = np.full((n, len(fr_list)), NO_LIMIT, dtype=np.int64)
    borrow = np.full((n, len(fr_list)), NO_LIMIT, dtype=np.int64)
    weight = np.full(n, 1000, dtype=np.int64)

    cq_models: Dict[str, ClusterQueue] = {}
    for name in flat.cq_names:
        model = cache.cluster_queues[name].model
        cq_models[name] = model
        row = flat.index[name]
        _quota_cells(_collect_quotas(model.resource_groups), fr_index, nominal, lend, borrow, row)
        weight[row] = model.fair_sharing.weight_milli
    for cname in flat.cohort_names:
        cohort = cache.cohorts.get(cname)
        if cohort is not None:
            row = flat.index[cname]
            _quota_cells(_collect_quotas(cohort.resource_groups), fr_index, nominal, lend, borrow, row)
            weight[row] = cohort.fair_sharing.weight_milli

    level_mask = flat.level_masks()
    subtree, guaranteed = subtree_quota_np(flat.parent, level_mask, nominal, lend)

    snap = Snapshot(
        flat=flat,
        fr_list=fr_list,
        fr_index=fr_index,
        resource_names=resource_names,
        resource_index=resource_index,
        nominal=nominal,
        lending_limit=lend,
        borrowing_limit=borrow,
        subtree=subtree,
        guaranteed=guaranteed,
        local_usage=np.zeros((n, len(fr_list)), dtype=np.int64),
        weight_milli=weight,
        cq_models=cq_models,
        inactive_cqs=tuple(inactive),
        generations={
            name: cache.cluster_queues[name].allocatable_generation
            for name in flat.cq_names
        },
        priority_classes=dict(cache.priority_classes),
    )

    from kueue_tpu.models.constants import WorkloadConditionType
    from kueue_tpu.utils.priority import priority_of

    for name in flat.cq_names:
        cached = cache.cluster_queues[name]
        for wl in cached.workloads.values():
            usage = admission_usage(wl)
            qr = wl.conditions.get(WorkloadConditionType.QUOTA_RESERVED)
            snap.add_workload(
                WorkloadSnapshot(
                    workload=wl,
                    cq_name=name,
                    cq_row=flat.index[name],
                    usage_vec=snap.vector_of(usage),
                    priority=priority_of(wl, cache.priority_classes),
                    quota_reserved_time=qr.last_transition_time if qr else wl.creation_time,
                )
            )
    return snap
