"""Shared snapshot <-> device-array codec.

One definition of the dense array layout the device kernels consume —
the cohort forest as parent links + level masks, the quota triple and
leaf usage as int64[N, FR] matrices — extracted from the solver's
inlined encoding so the live cycle dispatch, the bulk drain and the
capacity planner all read the SAME bytes for the same snapshot and
cannot drift. ``encode_snapshot`` is view-based (no copies) so the hot
path pays nothing for the indirection; ``decode_snapshot`` rebuilds an
independent, fully functional ``Snapshot`` from the arrays (the
planner's per-scenario host snapshots; round-trip equality is asserted
in tests/test_encode.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple

import numpy as np

from kueue_tpu.core.hierarchy import FlatHierarchy
from kueue_tpu.core.snapshot import Snapshot, WorkloadSnapshot
from kueue_tpu.ops.quota_np import subtree_quota_np
from kueue_tpu.resources import FlavorResource

__all__ = [
    "EncodedSnapshot",
    "ResidentEncoder",
    "encode_snapshot",
    "decode_snapshot",
    "device_arrays",
    "encode_candidate_scores",
    "encode_candidate_scores_multi",
]


@dataclass
class EncodedSnapshot:
    """The snapshot's dense-tensor essence.

    Array fields are the device payload (what the kernels consume);
    the name tuples + host-object maps carry exactly enough identity to
    decode back into a ``Snapshot``. Quota/usage arrays are VIEWS of
    the source snapshot by default — callers mutating them (the planner
    stacking scenario variants) must copy first (``with_quota``).
    """

    cq_names: Tuple[str, ...]
    cohort_names: Tuple[str, ...]
    fr_list: Tuple[FlavorResource, ...]
    parent: np.ndarray  # int32[N]
    level_mask: np.ndarray  # bool[D+1, N]
    nominal: np.ndarray  # int64[N, FR]
    lending_limit: np.ndarray  # int64[N, FR]
    borrowing_limit: np.ndarray  # int64[N, FR]
    local_usage: np.ndarray  # int64[N, FR]
    weight_milli: np.ndarray  # int64[N]
    generations: Dict[str, int] = field(default_factory=dict)
    inactive_cqs: Tuple[str, ...] = ()
    # host-only references (never shipped to the device)
    cq_models: Dict[str, object] = field(default_factory=dict)
    priority_classes: Dict[str, object] = field(default_factory=dict)
    workloads: Dict[str, WorkloadSnapshot] = field(default_factory=dict)

    @property
    def n_cq(self) -> int:
        return len(self.cq_names)

    @property
    def n_nodes(self) -> int:
        return self.parent.shape[0]

    @property
    def max_depth(self) -> int:
        return self.level_mask.shape[0] - 1

    def row(self, name: str) -> int:
        names = self.cq_names
        try:
            return names.index(name)
        except ValueError:
            return self.n_cq + self.cohort_names.index(name)

    def with_quota(
        self,
        nominal: Optional[np.ndarray] = None,
        lending_limit: Optional[np.ndarray] = None,
        borrowing_limit: Optional[np.ndarray] = None,
        local_usage: Optional[np.ndarray] = None,
        weight_milli: Optional[np.ndarray] = None,
    ) -> "EncodedSnapshot":
        """A variant sharing structure but carrying replacement quota /
        usage arrays — how the planner materializes one scenario."""
        return replace(
            self,
            nominal=self.nominal if nominal is None else nominal,
            lending_limit=(
                self.lending_limit if lending_limit is None else lending_limit
            ),
            borrowing_limit=(
                self.borrowing_limit if borrowing_limit is None else borrowing_limit
            ),
            local_usage=(
                self.local_usage if local_usage is None else local_usage
            ),
            weight_milli=(
                self.weight_milli if weight_milli is None else weight_milli
            ),
        )


def encode_snapshot(snapshot: Snapshot) -> EncodedSnapshot:
    """Snapshot -> dense arrays (views; zero-copy)."""
    flat = snapshot.flat
    return EncodedSnapshot(
        cq_names=flat.cq_names,
        cohort_names=flat.cohort_names,
        fr_list=snapshot.fr_list,
        parent=flat.parent,
        level_mask=flat.level_masks(),
        nominal=snapshot.nominal,
        lending_limit=snapshot.lending_limit,
        borrowing_limit=snapshot.borrowing_limit,
        local_usage=snapshot.local_usage,
        weight_milli=snapshot.weight_milli,
        generations=snapshot.generations,
        inactive_cqs=snapshot.inactive_cqs,
        cq_models=snapshot.cq_models,
        priority_classes=snapshot.priority_classes,
        workloads=snapshot.workloads,
    )


def decode_snapshot(enc: EncodedSnapshot) -> Snapshot:
    """Arrays -> an independent Snapshot (array fields copied, so the
    result is safely mutable: the planner's forecast simulation
    add/remove-usage loops run on decoded scenario snapshots without
    touching the live state)."""
    index = {name: i for i, name in enumerate(enc.cq_names)}
    for j, name in enumerate(enc.cohort_names):
        index[name] = enc.n_cq + j
    parent = np.array(enc.parent, dtype=np.int32, copy=True)
    n = parent.shape[0]
    depth = np.zeros(n, dtype=np.int32)
    for i in range(n):
        d, cur = 0, int(parent[i])
        while cur >= 0:
            d += 1
            cur = int(parent[cur])
        depth[i] = d
    flat = FlatHierarchy(
        cq_names=tuple(enc.cq_names),
        cohort_names=tuple(enc.cohort_names),
        index=index,
        parent=parent,
        depth=depth,
        max_depth=int(depth.max()) if n else 0,
        inactive_cqs=(),
    )
    nominal = np.array(enc.nominal, dtype=np.int64, copy=True)
    lend = np.array(enc.lending_limit, dtype=np.int64, copy=True)
    borrow = np.array(enc.borrowing_limit, dtype=np.int64, copy=True)
    subtree, guaranteed = subtree_quota_np(
        parent, flat.level_masks(), nominal, lend
    )
    fr_list = tuple(enc.fr_list)
    fr_index = {fr: j for j, fr in enumerate(fr_list)}
    resource_names = tuple(sorted({fr.resource for fr in fr_list}))
    rname_index = {r: i for i, r in enumerate(resource_names)}
    resource_index = np.array(
        [rname_index[fr.resource] for fr in fr_list], dtype=np.int32
    )
    snap = Snapshot(
        flat=flat,
        fr_list=fr_list,
        fr_index=fr_index,
        resource_names=resource_names,
        resource_index=resource_index,
        nominal=nominal,
        lending_limit=lend,
        borrowing_limit=borrow,
        subtree=subtree,
        guaranteed=guaranteed,
        local_usage=np.array(enc.local_usage, dtype=np.int64, copy=True),
        weight_milli=np.array(enc.weight_milli, dtype=np.int64, copy=True),
        cq_models=dict(enc.cq_models),
        inactive_cqs=tuple(enc.inactive_cqs),
        generations=dict(enc.generations),
        priority_classes=dict(enc.priority_classes),
    )
    # workload registrations WITHOUT re-adding usage: local_usage above
    # already carries their charge (add_workload would double-count)
    for key, ws in enc.workloads.items():
        snap.workloads[key] = ws
        snap._by_cq.setdefault(ws.cq_name, {})[key] = ws
    return snap


def device_arrays(enc: EncodedSnapshot):
    """(QuotaTree, paths, roots) — the device inputs every kernel
    consumer (cycle dispatch, drain, planner) builds through here."""
    from kueue_tpu._jax import jnp
    from kueue_tpu.ops.assign_kernel import build_paths, build_roots
    from kueue_tpu.ops.quota import QuotaTree

    tree = QuotaTree(
        parent=jnp.asarray(enc.parent),
        level_mask=jnp.asarray(enc.level_mask),
        nominal=jnp.asarray(enc.nominal),
        lending_limit=jnp.asarray(enc.lending_limit),
        borrowing_limit=jnp.asarray(enc.borrowing_limit),
    )
    paths = jnp.asarray(build_paths(enc.parent, enc.max_depth))
    roots = build_roots(enc.parent)
    return tree, paths, roots


# ---- admission-policy score tensors (kueue_tpu/policy) ----
# The policy subsystem's declarative inputs (per-flavor throughput,
# deadlines, remaining work — workload labels) enter the device path
# HERE: compiled once per lowered batch into dense int64 score tensors
# the scored kernels argmax over. Like the quota codec above, this is
# the single definition both the cycle dispatch (core/solver.pack_heads)
# and the bulk drain (core/drain.plan_drain) ship, so device kernels
# and their numpy mirrors read the SAME bytes for the same policy.


def _flavor_sig(flavor_map: dict) -> Tuple[str, ...]:
    """A candidate's distinct flavor names (one flavor per touched
    resource group; dict values repeat per resource)."""
    return tuple(sorted(set(flavor_map.values())))


def _template_sigs(flist, n_k: int, sig_cache: dict):
    """(k, flavor_sig) tuple of a template-shared candidate flavor
    list — computed ONCE per list identity (lowering shares one list
    per template, so a 50k-head backlog resolves this O(templates)
    times). The returned tuple is hashable: score rows cache on IT,
    not on template identity, so the hundreds of per-CQ templates that
    enumerate the same flavors share one compiled row."""
    sigs = sig_cache.get(id(flist))
    if sigs is None:
        sigs = sig_cache[id(flist)] = tuple(
            (k, _flavor_sig(fmap))
            for k, fmap in enumerate(flist[:n_k])
            if fmap
        )
    return sigs


def encode_candidate_scores(
    policy, heads, candidate_flavors, n_k: int
) -> np.ndarray:
    """int64[W, K] candidate scores for a cycle batch.

    ``candidate_flavors[i][k]`` is the lowered {resource: flavor} map
    (core/solver.Lowered). Candidate flavor signatures memoize per
    template-shared list identity and scores per (workload labels,
    flavor set), so compilation is O(templates + distinct pairs), not
    O(heads x candidates)."""
    w = len(heads)
    score = np.zeros((w, n_k), dtype=np.int64)
    cache: dict = {}
    sig_cache: dict = {}
    for i, wl in enumerate(heads):
        flist = candidate_flavors[i]
        if not flist:
            continue
        labels = getattr(wl, "labels", None)
        labels_sig = tuple(sorted(labels.items())) if labels else ()
        for k, fsig in _template_sigs(flist, n_k, sig_cache):
            key = (labels_sig, fsig)
            s = cache.get(key)
            if s is None:
                s = cache[key] = int(policy.candidate_score(wl, fsig))
            score[i, k] = s
    return score


def encode_candidate_scores_multi(policy, lowered) -> np.ndarray:
    """int64[W, P, K] candidate scores for a drain batch
    (core/solver.MultiLowered): every podset's candidate walk scores
    independently, exactly like its flavor walk.

    Bulk discipline (the 50k-head drain must not pay a python loop per
    candidate): heads grouped by (label signature, template flavor
    list) share ONE computed score row, scattered with fancy indexing —
    compilation is O(heads) dict appends + O(distinct groups) policy
    calls."""
    w, pmax, n_k = lowered.valid.shape
    score = np.zeros((w, pmax, n_k), dtype=np.int64)
    sig_cache: dict = {}
    groups: dict = {}  # (labels_sig, candidate sigs, p) -> [head idx]
    rep: dict = {}  # group key -> representative workload
    for i, wl in enumerate(lowered.heads):
        per_ps = lowered.candidate_flavors[i]
        if not per_ps:
            continue
        labels = getattr(wl, "labels", None)
        labels_sig = tuple(sorted(labels.items())) if labels else ()
        for p, flist in enumerate(per_ps[:pmax]):
            key = (labels_sig, _template_sigs(flist, n_k, sig_cache), p)
            g = groups.get(key)
            if g is None:
                g = groups[key] = []
                rep[key] = wl
            g.append(i)
    row_cache: dict = {}
    for key, idxs in groups.items():
        labels_sig, sigs, p = key
        rkey = (labels_sig, sigs)
        row = row_cache.get(rkey)
        if row is None:
            wl = rep[key]
            row = np.zeros(n_k, dtype=np.int64)
            for k, fsig in sigs:
                row[k] = int(policy.candidate_score(wl, fsig))
            row_cache[rkey] = row
        score[np.asarray(idxs, dtype=np.intp), p] = row
    return score


def _pow2(n: int, minimum: int = 4) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


_SCATTER_JIT = None


def _scatter_rows_jit():
    """Lazy jit (the module stays importable without configuring JAX).
    No buffer donation: the pipelined loop may refresh while a
    speculative launch still references the previous usage buffer, and
    the resident buffers must never alias an in-flight solve's
    inputs."""
    global _SCATTER_JIT
    if _SCATTER_JIT is None:
        from kueue_tpu._jax import jax

        _SCATTER_JIT = jax.jit(lambda u, idx, rows: u.at[idx].set(rows))
    return _SCATTER_JIT


class ResidentEncoder:
    """Device-resident drain encode for the pipelined loop (the PR-7
    follow-up): the quota tree + ancestor paths stay ON DEVICE between
    drain rounds, and each round ships only the leaf-usage rows the
    previous commit touched (a bucketed row scatter) instead of a full
    ``encode_snapshot`` -> ``device_arrays`` re-encode.

    ``refresh(snapshot)`` returns ``(tree, paths, usage_dev)`` whose
    array content is BYTE-IDENTICAL to a fresh encode of the same
    snapshot (asserted in tests/test_mesh_drain.py): the delta path
    only ever fires when the structure fingerprint — CQ row order,
    cohort edges, the quota triple — is unchanged, and ANY config
    mutation falls back to a full re-encode. SINGLE-DEVICE ONLY: the
    mesh path re-places inputs with their shardings every round
    (``device_put`` onto shards IS its transfer plan) — passing a
    resident together with a mesh raises in ``launch_drain`` /
    ``launch_drain_megaloop`` rather than silently ignoring it.

    The megaloop (ops/megaloop_kernel) extends the residency to the
    usage itself: the kernel carries leaf usage across K fused rounds
    on device, and after a fully-committed launch ``adopt`` takes the
    kernel's final-usage device slice as the resident buffer — the
    next ``refresh`` then diffs against exactly the post-apply state
    and ships zero rows."""

    def __init__(self):
        self._names = None
        self._parent = None
        self._quota_key = None  # (nominal, lending, borrowing) copies
        self._tree = None
        self._paths = None
        self._usage = None  # device [N, FR]
        self._usage_host = None  # numpy mirror of the device content
        # telemetry (SIGUSR2 dump / BENCH notes)
        self.full_encodes = 0
        self.delta_rounds = 0
        self.delta_rows = 0
        self.adopts = 0

    def _structure_matches(self, enc: EncodedSnapshot) -> bool:
        if self._names != tuple(enc.cq_names) + tuple(enc.cohort_names):
            return False
        if self._usage_host is None or (
            self._usage_host.shape != enc.local_usage.shape
        ):
            return False
        if not np.array_equal(self._parent, enc.parent):
            return False
        nom, lend, bor = self._quota_key
        return (
            np.array_equal(nom, enc.nominal)
            and np.array_equal(lend, enc.lending_limit)
            and np.array_equal(bor, enc.borrowing_limit)
        )

    def refresh(self, snapshot: Snapshot):
        """(tree, paths, usage_dev) with minimal transfer."""
        from kueue_tpu._jax import jnp

        enc = encode_snapshot(snapshot)
        if not self._structure_matches(enc):
            self._tree, self._paths, _ = device_arrays(enc)
            self._usage = jnp.asarray(enc.local_usage)
            self._usage_host = enc.local_usage.copy()
            self._names = tuple(enc.cq_names) + tuple(enc.cohort_names)
            self._parent = np.array(enc.parent, copy=True)
            self._quota_key = (
                enc.nominal.copy(),
                enc.lending_limit.copy(),
                enc.borrowing_limit.copy(),
            )
            self.full_encodes += 1
            return self._tree, self._paths, self._usage

        new = enc.local_usage
        changed = (new != self._usage_host).any(axis=1)
        idx = np.flatnonzero(changed)
        if idx.size:
            if idx.size > max(16, new.shape[0] // 4):
                # bulk change: a fresh upload beats a huge scatter
                self._usage = jnp.asarray(new)
            else:
                # bucket the delta width (pad by repeating the first
                # changed row — idempotent under .set) so the scatter
                # compiles once per bucket, not per changed-row count
                n = _pow2(int(idx.size))
                idx_p = np.concatenate(
                    [idx, np.full(n - idx.size, idx[0], dtype=idx.dtype)]
                ).astype(np.int32)
                self._usage = _scatter_rows_jit()(
                    self._usage, jnp.asarray(idx_p), jnp.asarray(new[idx_p])
                )
            self._usage_host = new.copy()
            self.delta_rows += int(idx.size)
        self.delta_rounds += 1
        return self._tree, self._paths, self._usage

    def adopt(self, usage_dev, usage_host: np.ndarray) -> None:
        """In-loop usage carry (the megaloop's post-commit hand-off):
        after every round of a fused launch committed, the kernel's
        final leaf usage IS the post-apply state — the per-round
        conflict checks proved it byte-for-byte — so the resident
        buffer adopts the device slice directly and the next
        ``refresh`` ships zero rows. A truncated batch must NOT adopt
        (the real state diverged mid-batch); ``refresh`` re-diffs."""
        if self._usage_host is None or (
            self._usage_host.shape != usage_host.shape
        ):
            return  # no resident structure yet: next refresh rebuilds
        self._usage = usage_dev
        self._usage_host = np.asarray(usage_host, dtype=np.int64).copy()
        self.adopts += 1

    def stats(self) -> dict:
        return {
            "fullEncodes": self.full_encodes,
            "deltaRounds": self.delta_rounds,
            "deltaRows": self.delta_rows,
            "adopts": self.adopts,
        }
