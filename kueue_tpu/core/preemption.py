"""Preemption — classic minimal-set and fair-sharing victim search.

Behavioral equivalent of ``pkg/scheduler/preemption``:

- candidate discovery respecting withinClusterQueue / reclaimWithinCohort
  policies and the flavor-resources actually needing preemption
  (preemption.go:480-524)
- candidate ordering: evicted first, other-CQ first, lowest priority,
  most recently reserved (preemption.go:591-618)
- classic strategy ladder: same-queue-with-borrowing /
  borrowWithinCohort thresholds / cohort-reclaim-without-borrowing /
  same-queue fallback (preemption.go:144-191)
- minimalPreemptions remove-then-fill-back heuristic over the snapshot
  (preemption.go:275-342) — here simulate/undo is vector add/sub on the
  dense usage matrix instead of object-graph mutation
- fair sharing: the cohort-tree tournament picking the highest-DRS
  subtree, almost-LCA share comparisons, strategies S2-a
  (LessThanOrEqualToFinalShare) and S2-b (LessThanInitialShare)
  (fairsharing/ordering.go, least_common_ancestor.go, strategy.go)
- the reclaim oracle answering flavor assignment's "is reclaim
  possible" (preemption_oracle.go)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from kueue_tpu.models import Workload
from kueue_tpu.models.constants import (
    EVICTED_BY_PREEMPTION,
    BorrowWithinCohortPolicy,
    PreemptionPolicy,
    ReclaimWithinCohortPolicy,
    WorkloadConditionType,
)
from kueue_tpu.core.flavor_assigner import AssignmentResult, Mode
from kueue_tpu.core.queue_manager import RequeueTimestamp, queue_order_timestamp
from kueue_tpu.core.scheduler import PreemptionTarget, Preemptor as PreemptorBase
from kueue_tpu.core.snapshot import Snapshot, WorkloadSnapshot
from kueue_tpu.resources import FlavorResource
from kueue_tpu.utils.clock import Clock

# Preemption reasons (workload_types.go Preempted condition reasons).
IN_CLUSTER_QUEUE = "InClusterQueue"
IN_COHORT_RECLAMATION = "InCohortReclamation"
IN_COHORT_FAIR_SHARING = "InCohortFairSharing"
IN_COHORT_RECLAIM_WHILE_BORROWING = "InCohortReclaimWhileBorrowing"

# Fair-sharing preemption strategies (config fairSharing.preemptionStrategies).
LESS_THAN_OR_EQUAL_TO_FINAL_SHARE = "LessThanOrEqualToFinalShare"
LESS_THAN_INITIAL_SHARE = "LessThanInitialShare"


@dataclass
class _Ctx:
    preemptor: Workload
    cq_name: str
    cq_row: int
    snapshot: Snapshot
    frs_need_preemption: Set[FlavorResource]
    usage_vec: np.ndarray


def can_always_reclaim(cq) -> bool:
    """preemption.CanAlwaysReclaim: reclaimWithinCohort=Any guarantees
    capacity can be taken back later, so no reservation is needed."""
    return cq.preemption.reclaim_within_cohort == ReclaimWithinCohortPolicy.ANY


class Preemptor(PreemptorBase):
    def __init__(
        self,
        clock: Clock,
        enable_fair_sharing: bool = False,
        fs_strategies: Optional[Sequence[str]] = None,
        apply_preemption: Optional[Callable[[Workload, str, str], bool]] = None,
        timestamp_policy: RequeueTimestamp = RequeueTimestamp.EVICTION,
        events: Optional[Callable[[str, Workload, str], None]] = None,
    ):
        self.clock = clock
        self.enable_fair_sharing = enable_fair_sharing
        self.fs_strategies = list(
            fs_strategies
            or [LESS_THAN_OR_EQUAL_TO_FINAL_SHARE, LESS_THAN_INITIAL_SHARE]
        )
        self.apply_preemption = apply_preemption or (lambda wl, reason, msg: True)
        self._ts_policy = timestamp_policy
        self.events = events or (lambda kind, wl, msg: None)
        # (preempting_cq, reason, victim) -> None; set by the runtime to
        # report preempted_workloads_total / evicted_workloads_total
        self.metrics_hook = None
        # admission policy (kueue_tpu/policy): PREMA-style victim-cost
        # adjustments in the candidate ordering; None/first-fit = the
        # unadjusted reference order
        self.policy = None

    # ---- entry point (preemption.go:127-191) ----
    def get_targets(
        self, wl: Workload, cq_name: str, assignment: AssignmentResult, snapshot: Snapshot
    ) -> List[PreemptionTarget]:
        frs = self._frs_need_preemption(assignment)
        ctx = _Ctx(
            preemptor=wl,
            cq_name=cq_name,
            cq_row=snapshot.row(cq_name),
            snapshot=snapshot,
            frs_need_preemption=frs,
            usage_vec=snapshot.vector_of(assignment.usage),
        )
        return self._get_targets(ctx)

    def _get_targets(self, ctx: _Ctx) -> List[PreemptionTarget]:
        candidates = self._find_candidates(ctx)
        if not candidates:
            return []
        candidates.sort(key=self._candidate_key(ctx))
        if self.enable_fair_sharing:
            return self._fair_preemptions(ctx, candidates)

        cq = ctx.snapshot.cq_models[ctx.cq_name]
        same_queue = [c for c in candidates if c.cq_name == ctx.cq_name]

        if len(same_queue) == len(candidates):
            return self._minimal_preemptions(ctx, candidates, True, None)

        allowed, threshold = self._can_borrow_within_cohort(cq, ctx)
        if allowed:
            if not self._queue_under_nominal(ctx):
                candidates = [
                    c
                    for c in candidates
                    if c.cq_name == ctx.cq_name or c.priority < threshold
                ]
            return self._minimal_preemptions(ctx, candidates, True, threshold)

        if self._queue_under_nominal(ctx):
            targets = self._minimal_preemptions(ctx, candidates, False, None)
            if targets:
                return targets

        return self._minimal_preemptions(ctx, same_queue, True, None)

    # ---- issue (preemption.go:232-265) ----
    def issue_preemptions(
        self, preemptor: Workload, targets: List[PreemptionTarget],
        preempting_cq: str = "",
    ) -> int:
        count = 0
        now = self.clock.now()
        for t in targets:
            wl = t.workload.workload
            if wl.condition_true(WorkloadConditionType.EVICTED):
                count += 1  # preemption already ongoing
                continue
            msg = (
                f"Preempted to accommodate a workload (UID: {preemptor.uid}) "
                f"due to {t.reason}"
            )
            if self.apply_preemption(wl, t.reason, msg):
                wl.set_condition(
                    WorkloadConditionType.EVICTED, True,
                    reason=EVICTED_BY_PREEMPTION, message=msg, now=now,
                )
                wl.set_condition(
                    WorkloadConditionType.PREEMPTED, True,
                    reason=t.reason, message=msg, now=now,
                )
                # checks reset on eviction (ResetChecksOnEviction)
                for st in wl.admission_check_states.values():
                    from kueue_tpu.models.constants import AdmissionCheckStateType

                    st.state = AdmissionCheckStateType.PENDING
                self.events("Preempted", wl, msg)
                if self.metrics_hook is not None:
                    self.metrics_hook(preempting_cq, t.reason, wl)
                count += 1
        return count

    # ---- oracle (preemption_oracle.go) ----
    def is_reclaim_possible(
        self, snapshot: Snapshot, cq_name: str, wl: Optional[Workload], fr: FlavorResource, quantity: int
    ) -> bool:
        j = snapshot.fr_index.get(fr)
        if j is None:
            return False
        r = snapshot.row(cq_name)
        if int(snapshot.local_usage[r, j]) + quantity > int(snapshot.nominal[r, j]):
            return False  # would borrow: not pure reclamation
        usage_vec = np.zeros(len(snapshot.fr_list), dtype=np.int64)
        usage_vec[j] = quantity
        ctx = _Ctx(
            preemptor=wl,
            cq_name=cq_name,
            cq_row=r,
            snapshot=snapshot,
            frs_need_preemption={fr},
            usage_vec=usage_vec,
        )
        for t in self._get_targets(ctx):
            if t.workload.cq_name == cq_name:
                return False
        return True

    # ---- candidates (preemption.go:480-547) ----
    def _frs_need_preemption(self, assignment: AssignmentResult) -> Set[FlavorResource]:
        out: Set[FlavorResource] = set()
        for ps in assignment.pod_sets:
            for res, choice in ps.flavors.items():
                if choice.mode.public() == Mode.PREEMPT:
                    out.add(FlavorResource(choice.name, res))
        return out

    def _workload_uses(self, ws: WorkloadSnapshot, frs: Set[FlavorResource]) -> bool:
        if ws.workload.admission is None:
            return False
        for psa in ws.workload.admission.pod_set_assignments:
            for res, flavor in psa.flavors.items():
                if FlavorResource(flavor, res) in frs:
                    return True
        return False

    def _cq_is_borrowing(
        self, snapshot: Snapshot, cq_name: str, frs: Set[FlavorResource]
    ) -> bool:
        if not snapshot.has_cohort(cq_name):
            return False
        r = snapshot.row(cq_name)
        for fr in frs:
            j = snapshot.fr_index.get(fr)
            if j is not None and int(snapshot.local_usage[r, j]) > int(
                snapshot.nominal[r, j]
            ):
                return True
        return False

    def _find_candidates(self, ctx: _Ctx) -> List[WorkloadSnapshot]:
        snapshot = ctx.snapshot
        cq = snapshot.cq_models[ctx.cq_name]
        out: List[WorkloadSnapshot] = []
        from kueue_tpu.utils.priority import priority_of

        wl_priority = priority_of(ctx.preemptor, snapshot.priority_classes)
        preemptor_ts = queue_order_timestamp(ctx.preemptor, self._ts_policy)

        if cq.preemption.within_cluster_queue != PreemptionPolicy.NEVER:
            consider_same_prio = (
                cq.preemption.within_cluster_queue
                == PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY
            )
            for ws in snapshot.workloads_in_cq(ctx.cq_name):
                if ws.priority > wl_priority:
                    continue
                if ws.priority == wl_priority and not (
                    consider_same_prio
                    and preemptor_ts
                    < queue_order_timestamp(ws.workload, self._ts_policy)
                ):
                    continue
                if not self._workload_uses(ws, ctx.frs_need_preemption):
                    continue
                out.append(ws)

        if (
            snapshot.has_cohort(ctx.cq_name)
            and cq.preemption.reclaim_within_cohort != ReclaimWithinCohortPolicy.NEVER
        ):
            only_lower = (
                cq.preemption.reclaim_within_cohort != ReclaimWithinCohortPolicy.ANY
            )
            for member in snapshot.cohort_members(ctx.cq_name):
                if member == ctx.cq_name:
                    continue
                if not self._cq_is_borrowing(snapshot, member, ctx.frs_need_preemption):
                    continue
                for ws in snapshot.workloads_in_cq(member):
                    if only_lower and ws.priority >= wl_priority:
                        continue
                    if not self._workload_uses(ws, ctx.frs_need_preemption):
                        continue
                    out.append(ws)
        return out

    def _candidate_key(self, ctx: _Ctx):
        policy = self.policy
        scoring = policy is not None and not policy.is_default

        def key(ws: WorkloadSnapshot):
            evicted = ws.workload.condition_true(WorkloadConditionType.EVICTED)
            in_cq = ws.cq_name == ctx.cq_name
            # PREMA victim-cost adjustment (kueue_tpu/policy): between
            # the (evicted, other-CQ) tiers and priority; zero under
            # the default policy, so the order is exactly the
            # reference's (preemption.go:591-618)
            adjust = (
                policy.victim_cost_adjust(ws.workload) if scoring else 0
            )
            return (
                0 if evicted else 1,
                0 if not in_cq else 1,
                adjust,
                ws.priority,
                -ws.quota_reserved_time,
                ws.workload.uid,
            )

        return key

    def _can_borrow_within_cohort(self, cq, ctx: _Ctx) -> Tuple[bool, Optional[int]]:
        policy = cq.preemption.borrow_within_cohort
        if policy.policy == BorrowWithinCohortPolicy.NEVER:
            return False, None
        from kueue_tpu.utils.priority import priority_of

        threshold = priority_of(ctx.preemptor, ctx.snapshot.priority_classes)
        if (
            policy.max_priority_threshold is not None
            and policy.max_priority_threshold < threshold
        ):
            threshold = policy.max_priority_threshold + 1
        return True, threshold

    def _queue_under_nominal(self, ctx: _Ctx) -> bool:
        """True if the CQ is under nominal quota in every resource
        needing preemption (preemption.go:576-583)."""
        r = ctx.cq_row
        for fr in ctx.frs_need_preemption:
            j = ctx.snapshot.fr_index.get(fr)
            if j is not None and int(ctx.snapshot.local_usage[r, j]) >= int(
                ctx.snapshot.nominal[r, j]
            ):
                return False
        return True

    # ---- fit check under simulation (preemption.go:552-574) ----
    def _workload_fits(self, ctx: _Ctx, allow_borrowing: bool) -> bool:
        snapshot = ctx.snapshot
        r = ctx.cq_row
        avail = snapshot.available()[r]
        need = ctx.usage_vec > 0
        if not allow_borrowing:
            after = snapshot.local_usage[r] + ctx.usage_vec
            if bool(np.any((after > snapshot.nominal[r]) & need)):
                return False
        return bool(np.all(np.maximum(avail, 0)[need] >= ctx.usage_vec[need]))

    # ---- classic minimal preemptions (preemption.go:275-342) ----
    def _minimal_preemptions(
        self,
        ctx: _Ctx,
        candidates: List[WorkloadSnapshot],
        allow_borrowing: bool,
        allow_borrowing_below_priority: Optional[int],
    ) -> List[PreemptionTarget]:
        snapshot = ctx.snapshot
        targets: List[PreemptionTarget] = []
        fits = False
        for ws in candidates:
            reason = IN_CLUSTER_QUEUE
            if ws.cq_name != ctx.cq_name:
                if not self._cq_is_borrowing(
                    snapshot, ws.cq_name, ctx.frs_need_preemption
                ):
                    continue
                reason = IN_COHORT_RECLAMATION
                if allow_borrowing_below_priority is not None:
                    if ws.priority >= allow_borrowing_below_priority:
                        allow_borrowing = False
                    else:
                        reason = IN_COHORT_RECLAIM_WHILE_BORROWING
            snapshot.remove_workload(ws.workload.key)
            targets.append(PreemptionTarget(workload=ws, reason=reason))
            if self._workload_fits(ctx, allow_borrowing):
                fits = True
                break
        if not fits:
            self._restore(snapshot, targets)
            return []
        targets = self._fill_back(ctx, targets, allow_borrowing)
        self._restore(snapshot, targets)
        return targets

    def _fill_back(
        self, ctx: _Ctx, targets: List[PreemptionTarget], allow_borrowing: bool
    ) -> List[PreemptionTarget]:
        snapshot = ctx.snapshot
        i = len(targets) - 2
        while i >= 0:
            snapshot.add_workload(targets[i].workload)
            if self._workload_fits(ctx, allow_borrowing):
                targets[i] = targets[-1]
                targets.pop()
            else:
                snapshot.remove_workload(targets[i].workload.workload.key)
            i -= 1
        return targets

    def _restore(self, snapshot: Snapshot, targets: List[PreemptionTarget]) -> None:
        for t in targets:
            snapshot.add_workload(t.workload)

    # ---- fair sharing (preemption.go:372-463 + fairsharing/) ----
    def _fair_preemptions(
        self, ctx: _Ctx, candidates: List[WorkloadSnapshot]
    ) -> List[PreemptionTarget]:
        snapshot = ctx.snapshot
        # DRS values must include the incoming workload's usage.
        snapshot.add_usage(ctx.cq_name, ctx.usage_vec)
        try:
            fits, targets, retry = self._run_first_fs_strategy(
                ctx, candidates, self.fs_strategies[0]
            )
            if not fits and len(self.fs_strategies) > 1:
                fits, targets = self._run_second_fs_strategy(ctx, retry, targets)
        finally:
            snapshot.remove_usage(ctx.cq_name, ctx.usage_vec)
        if not fits:
            self._restore(snapshot, targets)
            return []
        targets = self._fill_back(ctx, targets, True)
        self._restore(snapshot, targets)
        return targets

    def _fits_for_fair_sharing(self, ctx: _Ctx) -> bool:
        ctx.snapshot.remove_usage(ctx.cq_name, ctx.usage_vec)
        try:
            return self._workload_fits(ctx, True)
        finally:
            ctx.snapshot.add_usage(ctx.cq_name, ctx.usage_vec)

    def _run_first_fs_strategy(
        self, ctx: _Ctx, candidates: List[WorkloadSnapshot], strategy: str
    ):
        snapshot = ctx.snapshot
        targets: List[PreemptionTarget] = []
        retry: List[WorkloadSnapshot] = []
        ordering = _CohortTournament(ctx, candidates)
        while True:
            pick = ordering.next_target()
            if pick is None:
                return False, targets, retry
            if pick == ctx.cq_row:
                ws = ordering.pop_workload(pick)
                snapshot.remove_workload(ws.workload.key)
                targets.append(PreemptionTarget(workload=ws, reason=IN_CLUSTER_QUEUE))
                if self._fits_for_fair_sharing(ctx):
                    return True, targets, retry
                continue

            preemptor_share, target_old_share = ordering.compute_shares(pick)
            while ordering.has_workload(pick):
                ws = ordering.pop_workload(pick)
                snapshot.remove_workload(ws.workload.key)
                target_new_share = ordering.almost_lca_drs(pick)
                snapshot.add_workload(ws)
                if _strategy_allows(
                    strategy, preemptor_share, target_old_share, target_new_share
                ):
                    snapshot.remove_workload(ws.workload.key)
                    targets.append(
                        PreemptionTarget(workload=ws, reason=IN_COHORT_FAIR_SHARING)
                    )
                    if self._fits_for_fair_sharing(ctx):
                        return True, targets, retry
                    break  # re-pick the CQ: shares changed
                retry.append(ws)

    def _run_second_fs_strategy(
        self, ctx: _Ctx, retry: List[WorkloadSnapshot], targets: List[PreemptionTarget]
    ):
        snapshot = ctx.snapshot
        ordering = _CohortTournament(ctx, retry)
        while True:
            pick = ordering.next_target()
            if pick is None:
                return False, targets
            preemptor_share, target_old_share = ordering.compute_shares(pick)
            if preemptor_share < target_old_share:
                ws = ordering.pop_workload(pick)
                snapshot.remove_workload(ws.workload.key)
                targets.append(
                    PreemptionTarget(workload=ws, reason=IN_COHORT_FAIR_SHARING)
                )
                if self._fits_for_fair_sharing(ctx):
                    return True, targets
            ordering.drop_queue(pick)


def _strategy_allows(
    strategy: str, preemptor_new: int, target_old: int, target_new: int
) -> bool:
    if strategy == LESS_THAN_OR_EQUAL_TO_FINAL_SHARE:
        return preemptor_new <= target_new
    if strategy == LESS_THAN_INITIAL_SHARE:
        return preemptor_new < target_old
    raise ValueError(f"unknown fair-sharing strategy {strategy}")


class _CohortTournament:
    """The cohort-tree target ordering (fairsharing/ordering.go).

    Walks from the root picking the child subtree with the highest
    DominantResourceShare until reaching a ClusterQueue with remaining
    candidates. DRS values are recomputed per ``next_target`` call
    because removals during simulation shift usage at every ancestor —
    but only once per call: pruning between picks doesn't change usage.
    """

    def __init__(self, ctx: _Ctx, candidates: List[WorkloadSnapshot]):
        self.ctx = ctx
        self.snapshot = ctx.snapshot
        self.per_cq: Dict[int, List[WorkloadSnapshot]] = {}
        for ws in candidates:
            self.per_cq.setdefault(ws.cq_row, []).append(ws)
        self.pruned: Set[int] = set()
        self.preemptor_ancestors = set(self.snapshot.path_to_root(ctx.cq_row))
        # children adjacency, built once: O(N) instead of O(N) per query
        self.children: Dict[int, Tuple[List[int], List[int]]] = {}
        n_cq = self.snapshot.flat.n_cq
        for i, p in enumerate(self.snapshot.flat.parent):
            p = int(p)
            if p >= 0:
                entry = self.children.setdefault(p, ([], []))
                entry[0 if i < n_cq else 1].append(i)

    def has_workload(self, row: int) -> bool:
        return bool(self.per_cq.get(row))

    def pop_workload(self, row: int) -> WorkloadSnapshot:
        return self.per_cq[row].pop(0)

    def drop_queue(self, row: int) -> None:
        self.pruned.add(row)

    def next_target(self) -> Optional[int]:
        ctx = self.ctx
        if not self.snapshot.has_cohort(ctx.cq_name):
            return ctx.cq_row if self.has_workload(ctx.cq_row) else None
        root = self.snapshot.path_to_root(ctx.cq_row)[-1]
        drs = self.snapshot.all_node_drs()
        while root not in self.pruned:
            pick = self._next_in(root, drs)
            if pick is not None:
                return pick
        return None

    def _next_in(self, cohort_row: int, drs: np.ndarray) -> Optional[int]:
        cq_children, cohort_children = self.children.get(cohort_row, ([], []))
        best_cq, best_cq_drs = None, -1
        for row in cq_children:
            if row in self.pruned:
                continue
            d = int(drs[row])
            if (d == 0 and row != self.ctx.cq_row) or not self.has_workload(row):
                self.pruned.add(row)
            elif d >= best_cq_drs:
                best_cq_drs = d
                best_cq = row
        best_cohort, best_cohort_drs = None, -1
        for row in cohort_children:
            if row in self.pruned:
                continue
            d = int(drs[row])
            if d == 0 and row not in self.preemptor_ancestors:
                self.pruned.add(row)
            elif d >= best_cohort_drs:
                best_cohort_drs = d
                best_cohort = row
        if best_cohort is None and best_cq is None:
            self.pruned.add(cohort_row)
            return None
        if best_cohort is not None and best_cohort_drs >= best_cq_drs:
            return self._next_in(best_cohort, drs)
        return best_cq

    # ---- almost-LCA share computations (least_common_ancestor.go) ----
    def _lca(self, target_row: int) -> int:
        for anc in self.snapshot.path_to_root(target_row):
            if anc in self.preemptor_ancestors:
                return anc
        raise AssertionError("no common ancestor in cohort tree")

    def _almost_lca(self, row: int, lca: int) -> int:
        a = row
        for anc in self.snapshot.path_to_root(row):
            if anc == lca:
                return a
            a = anc
        raise AssertionError("lca not on path to root")

    def compute_shares(self, target_row: int) -> Tuple[int, int]:
        lca = self._lca(target_row)
        drs = self.snapshot.all_node_drs()
        return (
            int(drs[self._almost_lca(self.ctx.cq_row, lca)]),
            int(drs[self._almost_lca(target_row, lca)]),
        )

    def almost_lca_drs(self, target_row: int) -> int:
        lca = self._lca(target_row)
        drs = self.snapshot.all_node_drs()
        return int(drs[self._almost_lca(target_row, lca)])
