"""Pending-workload queues.

Behavioral equivalent of the reference's ``pkg/queue``: per-ClusterQueue
pending heaps with two pools (active heap + inadmissible parking lot),
StrictFIFO/BestEffortFIFO requeue policies, pop-cycle race avoidance,
eviction-backoff gating, and a manager owning LocalQueues, cohort-wide
reactivation and the Heads() handoff to the scheduler.

Mirrored semantics (no code ported):
- ordering: priority desc, then queue-order timestamp asc
  (pkg/queue/cluster_queue.go:413-426)
- requeue policy matrix by queueing strategy and reason
  (cluster_queue.go:402-407)
- popCycle / queueInadmissibleCycle: a workload requeued "generic"
  while a cohort-wide reactivation happened since its Pop goes back to
  the heap, not the parking lot (cluster_queue.go:225-252)
- backoffWaitingTimeExpired gates heap entry on RequeueState.requeueAt
  and the Requeued condition (cluster_queue.go:176-187)
- cohort-wide requeue: freeing capacity in one CQ reactivates parked
  workloads across the whole cohort tree (pkg/queue/manager.go:513-563)
"""

from __future__ import annotations

import threading
from enum import Enum
from typing import Callable, Dict, Iterable, List, Optional, Set

from kueue_tpu.models import ClusterQueue as ClusterQueueModel
from kueue_tpu.models import LocalQueue as LocalQueueModel
from kueue_tpu.models import QueueingStrategy, StopPolicy, Workload
from kueue_tpu.models.constants import WorkloadConditionType
from kueue_tpu.models.priority_class import WorkloadPriorityClass
from kueue_tpu.core.hierarchy import CohortForest
from kueue_tpu.utils.clock import Clock
from kueue_tpu.utils.heap import Heap
from kueue_tpu.utils.priority import priority_of


class RequeueReason(str, Enum):
    FAILED_AFTER_NOMINATION = "FailedAfterNomination"
    NAMESPACE_MISMATCH = "NamespaceMismatch"
    GENERIC = ""
    PENDING_PREEMPTION = "PendingPreemption"


class RequeueTimestamp(str, Enum):
    """waitForPodsReady.requeuingStrategy.timestamp config."""

    EVICTION = "Eviction"
    CREATION = "Creation"


def queue_order_timestamp(wl: Workload, policy: RequeueTimestamp) -> float:
    if policy == RequeueTimestamp.EVICTION:
        evicted = wl.conditions.get(WorkloadConditionType.EVICTED)
        if evicted is not None and evicted.status:
            return evicted.last_transition_time
    return wl.creation_time


class PendingClusterQueue:
    """One ClusterQueue's pending pools: active heap + parking lot."""

    def __init__(
        self,
        name: str,
        strategy: QueueingStrategy,
        clock: Clock,
        priority_fn: Callable[[Workload], int],
        timestamp_policy: RequeueTimestamp = RequeueTimestamp.EVICTION,
    ):
        self.name = name
        self.strategy = strategy
        self.clock = clock
        self._priority_fn = priority_fn
        self._ts_policy = timestamp_policy
        # native C++ heap when the shared library is available, else the
        # generic Python heap with the identical ordering
        from kueue_tpu.utils.native_heap import make_workload_heap

        self.heap = make_workload_heap(
            key_fn=lambda w: w.key,
            priority_fn=priority_fn,
            timestamp_fn=lambda w: queue_order_timestamp(w, timestamp_policy),
        )
        self.inadmissible: Dict[str, Workload] = {}
        self.pop_cycle = 0
        self.queue_inadmissible_cycle = -1
        self.inflight: Optional[Workload] = None
        self.active = True
        self.namespace_selector: Optional[Dict[str, str]] = None

    def _less(self, a: Workload, b: Workload) -> bool:
        """Strict ordering (cluster_queue.go:413-426); ties report
        neither-less so snapshot_sorted's stable sort preserves
        insertion order, matching the heaps' FIFO tie-break. Timestamps
        quantize to integer ns exactly like the heap ranks do, so heap
        pop order and snapshot ordering agree on near-ties."""
        pa, pb = self._priority_fn(a), self._priority_fn(b)
        if pa != pb:
            return pa > pb
        ta = int(queue_order_timestamp(a, self._ts_policy) * 1e9)
        tb = int(queue_order_timestamp(b, self._ts_policy) * 1e9)
        return ta < tb

    # ---- backoff gate ----
    def _backoff_expired(self, wl: Workload) -> bool:
        requeued = wl.conditions.get(WorkloadConditionType.REQUEUED)
        if requeued is not None and not requeued.status:
            return False
        if wl.requeue_state is None or wl.requeue_state.requeue_at is None:
            return True
        return self.clock.now() >= wl.requeue_state.requeue_at

    # ---- mutations ----
    def push_or_update(self, wl: Workload) -> None:
        key = wl.key
        self._forget_inflight(key)
        old = self.inadmissible.get(key)
        if old is not None:
            if old is wl:
                # In-place mutation (no API-server copies here): the
                # change test below can't fire — re-evaluate only the
                # backoff gate so a finished backoff unparks while
                # irrelevant updates stay parked.
                if not self._backoff_expired(wl):
                    return
            elif (
                # Stay parked if nothing admission-relevant changed
                # (spec / reclaimable pods / Evicted / Requeued conditions).
                old.pod_sets == wl.pod_sets
                and old.reclaimable_pods == wl.reclaimable_pods
                and old.priority == wl.priority
                and old.conditions.get(WorkloadConditionType.EVICTED)
                == wl.conditions.get(WorkloadConditionType.EVICTED)
                and old.conditions.get(WorkloadConditionType.REQUEUED)
                == wl.conditions.get(WorkloadConditionType.REQUEUED)
            ):
                self.inadmissible[key] = wl
                return
            del self.inadmissible[key]
        if self.heap.get_by_key(key) is None and not self._backoff_expired(wl):
            self.inadmissible[key] = wl
            return
        self.heap.push_or_update(wl)

    def delete(self, wl_key: str) -> None:
        self.inadmissible.pop(wl_key, None)
        self.heap.delete(wl_key)
        self._forget_inflight(wl_key)

    def requeue_if_not_present(self, wl: Workload, reason: RequeueReason) -> bool:
        if self.strategy == QueueingStrategy.STRICT_FIFO:
            immediate = reason != RequeueReason.NAMESPACE_MISMATCH
        else:
            immediate = reason in (
                RequeueReason.FAILED_AFTER_NOMINATION,
                RequeueReason.PENDING_PREEMPTION,
            )
        return self._requeue(wl, immediate)

    def _requeue(self, wl: Workload, immediate: bool) -> bool:
        key = wl.key
        self._forget_inflight(key)
        # A workload with untried flavors left in its fungibility cursor
        # retries immediately (cluster_queue.go:231 PendingFlavors).
        pending_flavors = (
            wl.last_assignment is not None
            and getattr(wl.last_assignment, "pending_flavors", lambda: False)()
        )
        if self._backoff_expired(wl) and (
            immediate
            or self.queue_inadmissible_cycle >= self.pop_cycle
            or pending_flavors
        ):
            parked = self.inadmissible.pop(key, None)
            if parked is not None:
                wl = parked
            return self.heap.push_if_not_present(wl)
        if key in self.inadmissible:
            return False
        if self.heap.get_by_key(key) is not None:
            return False
        self.inadmissible[key] = wl
        return True

    def queue_inadmissible(
        self, namespace_labels: Callable[[str], Dict[str, str]]
    ) -> bool:
        """Move parked workloads back to the heap (cluster conditions
        changed). Namespace-selector misses and unexpired backoffs stay
        parked."""
        self.queue_inadmissible_cycle = self.pop_cycle
        if not self.inadmissible:
            return False
        remaining: Dict[str, Workload] = {}
        moved = False
        for key, wl in self.inadmissible.items():
            ns_ok = self.namespace_selector is None or all(
                namespace_labels(wl.namespace).get(k) == v
                for k, v in self.namespace_selector.items()
            )
            if not ns_ok or not self._backoff_expired(wl):
                remaining[key] = wl
            else:
                moved = self.heap.push_if_not_present(wl) or moved
        self.inadmissible = remaining
        return moved

    def pop(self) -> Optional[Workload]:
        self.pop_cycle += 1
        head = self.heap.pop()
        self.inflight = head
        return head

    def _forget_inflight(self, key: str) -> None:
        if self.inflight is not None and self.inflight.key == key:
            self.inflight = None

    # ---- introspection ----
    def pending(self) -> int:
        return self.pending_active() + len(self.inadmissible)

    def pending_active(self) -> int:
        return len(self.heap) + (1 if self.inflight is not None else 0)

    def pending_inadmissible(self) -> int:
        return len(self.inadmissible)

    def snapshot_sorted(self) -> List[Workload]:
        items = list(self.heap.items()) + list(self.inadmissible.values())
        if self.inflight is not None:
            items.append(self.inflight)
        return self._heap_order(items)

    def snapshot_active_sorted(self) -> List[Workload]:
        """Active pending only (heap + inflight, no parked), in heap
        order — the workloads the cycle loop would pop as heads."""
        items = list(self.heap.items())
        if self.inflight is not None:
            items.append(self.inflight)
        return self._heap_order(items)

    def _heap_order(self, items: List[Workload]) -> List[Workload]:
        import functools

        return sorted(
            items,
            key=functools.cmp_to_key(
                lambda a, b: -1 if self._less(a, b) else (1 if self._less(b, a) else 0)
            ),
        )

    def park(self, wl: Workload) -> None:
        """Move a workload straight into inadmissible parking (the bulk
        drain's terminal NoFit outcome; the kernel already modeled the
        requeue/reactivation churn the host would run to get here)."""
        key = wl.key
        self.heap.delete(key)
        self._forget_inflight(key)
        self.inadmissible[key] = wl


class QueueManager:
    """Owns LocalQueues and per-CQ pending queues (pkg/queue/manager.go).

    Single authoritative pending-state store. ``heads()`` hands the
    scheduler the head workload of every active ClusterQueue;
    ``wait_for_heads`` blocks on a condition variable for runtime use.
    """

    def __init__(
        self,
        clock: Clock,
        priority_classes: Optional[Dict[str, WorkloadPriorityClass]] = None,
        timestamp_policy: RequeueTimestamp = RequeueTimestamp.EVICTION,
        namespace_labels: Optional[Callable[[str], Dict[str, str]]] = None,
    ):
        self.clock = clock
        self.priority_classes = priority_classes if priority_classes is not None else {}
        self._ts_policy = timestamp_policy
        self.namespace_labels = namespace_labels or (lambda ns: {})
        self.cluster_queues: Dict[str, PendingClusterQueue] = {}
        self.local_queues: Dict[str, LocalQueueModel] = {}
        self.lq_items: Dict[str, Dict[str, Workload]] = {}
        self.forest = CohortForest()
        self._cq_models: Dict[str, ClusterQueueModel] = {}
        self._cond = threading.Condition()

    def _priority(self, wl: Workload) -> int:
        return priority_of(wl, self.priority_classes)

    # ---- ClusterQueue lifecycle ----
    def add_cluster_queue(self, cq: ClusterQueueModel) -> None:
        pending = PendingClusterQueue(
            cq.name, cq.queueing_strategy, self.clock, self._priority, self._ts_policy
        )
        pending.namespace_selector = cq.namespace_selector
        pending.active = cq.stop_policy == StopPolicy.NONE
        self.cluster_queues[cq.name] = pending
        self._cq_models[cq.name] = cq
        self.forest.add_cluster_queue(cq.name, cq.cohort)
        # Adopt workloads from LocalQueues already pointing at this CQ
        # (manager.go AddClusterQueue requeues existing workloads).
        for lq_key, lq in self.local_queues.items():
            if lq.cluster_queue == cq.name:
                for wl in self.lq_items[lq_key].values():
                    pending.push_or_update(wl)
        self._broadcast()

    def update_cluster_queue(self, cq: ClusterQueueModel) -> None:
        pending = self.cluster_queues.get(cq.name)
        if pending is None:
            self.add_cluster_queue(cq)
            return
        pending.strategy = cq.queueing_strategy
        pending.namespace_selector = cq.namespace_selector
        pending.active = cq.stop_policy == StopPolicy.NONE
        self._cq_models[cq.name] = cq
        self.forest.update_cluster_queue(cq.name, cq.cohort)
        # Any spec change can make parked workloads admissible (new
        # quota, selector, strategy) — reactivate them all, mirroring
        # manager.UpdateClusterQueue's unconditional requeue.
        pending.queue_inadmissible(self.namespace_labels)
        self._broadcast()

    def delete_cluster_queue(self, name: str) -> None:
        self.cluster_queues.pop(name, None)
        self._cq_models.pop(name, None)
        self.forest.delete_cluster_queue(name)

    # ---- LocalQueue lifecycle ----
    def add_local_queue(
        self, lq: LocalQueueModel, workloads: Iterable[Workload] = ()
    ) -> None:
        self.local_queues[lq.key] = lq
        items = self.lq_items.setdefault(lq.key, {})
        for wl in workloads:
            items[wl.key] = wl
        pending = self.cluster_queues.get(lq.cluster_queue)
        if pending is not None:
            for wl in items.values():
                pending.push_or_update(wl)
            self._broadcast()

    def delete_local_queue(self, lq_key: str) -> None:
        lq = self.local_queues.pop(lq_key, None)
        items = self.lq_items.pop(lq_key, {})
        if lq is None:
            return
        pending = self.cluster_queues.get(lq.cluster_queue)
        if pending is not None:
            for key in items:
                pending.delete(key)

    def _lq_key_for(self, wl: Workload) -> str:
        return f"{wl.namespace}/{wl.queue_name}"

    # ---- Workload events (manager.go:374-470) ----
    def add_or_update_workload(self, wl: Workload) -> bool:
        lq = self.local_queues.get(self._lq_key_for(wl))
        if lq is None:
            return False
        self.lq_items.setdefault(lq.key, {})[wl.key] = wl
        if lq.stop_policy != StopPolicy.NONE:
            return False
        pending = self.cluster_queues.get(lq.cluster_queue)
        if pending is None:
            return False
        pending.push_or_update(wl)
        self._broadcast()
        return True

    def delete_workload(self, wl: Workload) -> None:
        lq = self.local_queues.get(self._lq_key_for(wl))
        if lq is not None:
            self.lq_items.get(lq.key, {}).pop(wl.key, None)
            pending = self.cluster_queues.get(lq.cluster_queue)
            if pending is not None:
                pending.delete(wl.key)

    def remove_from_pending(self, wl: Workload) -> None:
        """Drop a workload from its CQ's pending structures only (the
        admitted path: it stays a LocalQueue item, unlike
        delete_workload)."""
        lq = self.local_queues.get(self._lq_key_for(wl))
        if lq is None:
            return
        pending = self.cluster_queues.get(lq.cluster_queue)
        if pending is not None:
            pending.delete(wl.key)

    def park_workload(self, wl: Workload) -> None:
        """Terminal-NoFit parking for the bulk drain (see
        ClusterQueuePending.park)."""
        lq = self.local_queues.get(self._lq_key_for(wl))
        if lq is None:
            return
        pending = self.cluster_queues.get(lq.cluster_queue)
        if pending is not None:
            pending.park(wl)

    def requeue_workload(self, wl: Workload, reason: RequeueReason) -> bool:
        lq = self.local_queues.get(self._lq_key_for(wl))
        if lq is None or lq.stop_policy != StopPolicy.NONE:
            return False
        pending = self.cluster_queues.get(lq.cluster_queue)
        if pending is None:
            return False
        added = pending.requeue_if_not_present(wl, reason)
        if added:
            self._broadcast()
        return added

    # ---- cohort-wide reactivation (manager.go:466-563) ----
    def queue_associated_inadmissible_workloads_after(
        self, cq_name: str, mutate: Optional[Callable[[], None]] = None
    ) -> None:
        """After usage is freed in cq_name (workload finished/evicted),
        reactivate parked workloads in every CQ of its cohort tree."""
        if mutate is not None:
            mutate()
        cohort = self.forest.cq_parent.get(cq_name)
        if cohort is None:
            self._queue_inadmissible({cq_name})
            return
        root = self.forest.root_of(cohort)
        members = self._cohort_tree_cqs(root)
        self._queue_inadmissible(members)

    def queue_inadmissible_workloads(self, cq_names: Set[str]) -> None:
        self._queue_inadmissible(cq_names)

    def _cohort_tree_cqs(self, root_cohort: str) -> Set[str]:
        out: Set[str] = set()
        stack = [root_cohort]
        while stack:
            name = stack.pop()
            node = self.forest.cohorts.get(name)
            if node is None:
                continue
            out |= node.cq_children
            stack.extend(node.cohort_children)
        return out

    def _queue_inadmissible(self, cq_names: Set[str]) -> None:
        moved = False
        for name in cq_names:
            pending = self.cluster_queues.get(name)
            if pending is not None:
                moved = pending.queue_inadmissible(self.namespace_labels) or moved
        if moved:
            self._broadcast()

    # ---- scheduler handoff ----
    def heads(self) -> List[Workload]:
        """Pop the head of every active ClusterQueue (manager.go Heads)."""
        out: List[Workload] = []
        for name in sorted(self.cluster_queues):
            pending = self.cluster_queues[name]
            if not pending.active:
                continue
            head = pending.pop()
            if head is not None:
                out.append(head)
        return out

    def wait_for_heads(self, timeout: Optional[float] = None) -> List[Workload]:
        with self._cond:
            heads = self.heads()
            if heads:
                return heads
            self._cond.wait(timeout=timeout)
            return self.heads()

    def _broadcast(self) -> None:
        with self._cond:
            self._cond.notify_all()

    # ---- introspection / visibility ----
    def pending_workloads(self, cq_name: str) -> int:
        pending = self.cluster_queues.get(cq_name)
        return pending.pending() if pending else 0

    def cluster_queue_for_workload(self, wl: Workload) -> Optional[str]:
        lq = self.local_queues.get(self._lq_key_for(wl))
        return lq.cluster_queue if lq else None
