"""Service surface — HTTP/JSON server for the control plane.

The reference serves three network surfaces: the visibility embedded
apiserver (pkg/visibility/server.go:62-118), the metrics endpoint
(cmd/kueue/main.go:154-179), and the AdmissionCheck plugin boundary
that external controllers speak through the API server
(apis/kueue/v1beta1/admissioncheck_types.go:23-45). This package
provides the TPU-native framework's equivalents over plain HTTP/JSON:
a live object API feeding a ClusterRuntime, the visibility
pending-workloads API, a Prometheus text metrics endpoint, and the
``jax-assign`` solver service — the batched TPU nomination path
exposed as a stateless AdmissionCheck-style controller consuming
serialized snapshots.
"""

from kueue_tpu.server.app import KueueServer, solve_assign
from kueue_tpu.server.client import KueueClient

__all__ = ["KueueServer", "KueueClient", "solve_assign"]
