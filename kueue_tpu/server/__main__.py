"""python -m kueue_tpu.server — standalone control-plane server.

The cmd/kueue/main.go analog for the service surface: loads optional
state (--state, the CLI's JSON wire format), binds the HTTP server
(object API + visibility + metrics + jax-assign + dashboard), and
serves until interrupted.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kueue_tpu.server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8082)
    parser.add_argument(
        "--config",
        help="manager configuration file (kueue_tpu.config schema, "
        "the --config of cmd/kueue/main.go)",
    )
    parser.add_argument(
        "--state",
        help="JSON state file (CLI wire format): loaded at startup if "
        "present, written back on shutdown — the durable checkpoint "
        "active-passive recovery restarts from",
    )
    parser.add_argument(
        "--no-solver", action="store_true",
        help="disable the batched TPU nomination path",
    )
    parser.add_argument(
        "--no-auto-reconcile", action="store_true",
        help="only reconcile on POST /reconcile",
    )
    args = parser.parse_args(argv)

    import os

    from kueue_tpu import serialization as ser
    from kueue_tpu.server import KueueServer

    use_solver = False if args.no_solver else None
    if args.config:
        import yaml

        from kueue_tpu.config import load_config, runtime_from_config

        with open(args.config) as f:
            cfg = load_config(yaml.safe_load(f))
        runtime = runtime_from_config(cfg)
        if use_solver is not None:
            runtime.scheduler.use_solver = use_solver
    else:
        from kueue_tpu.controllers import ClusterRuntime

        runtime = ClusterRuntime(use_solver=use_solver)
    if args.state and os.path.exists(args.state):
        with open(args.state) as f:
            ser.runtime_from_state(json.load(f), runtime=runtime)
    srv = KueueServer(
        runtime=runtime,
        host=args.host,
        port=args.port,
        auto_reconcile=not args.no_auto_reconcile,
    )
    port = srv.start()
    print(f"kueue-tpu server listening on http://{args.host}:{port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    srv.stop()
    if args.state:
        # atomic checkpoint: never truncate the previous state before
        # the new one is fully on disk (a SIGKILL mid-write must not
        # destroy the only durable copy)
        tmp = args.state + ".tmp"
        with srv.lock:
            with open(tmp, "w") as f:
                json.dump(ser.runtime_to_state(runtime), f, indent=1)
        os.replace(tmp, args.state)
        print(f"state saved to {args.state}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
