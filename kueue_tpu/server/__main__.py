"""python -m kueue_tpu.server — standalone control-plane server.

The cmd/kueue/main.go analog for the service surface: loads optional
state (--state, the CLI's JSON wire format), binds the HTTP server
(object API + visibility + metrics + jax-assign + dashboard), and
serves until interrupted.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading


def fenced_checkpoint(srv, state_path: str) -> bool:
    """Atomically checkpoint srv.runtime to ``state_path``; returns
    False without writing when this replica no longer holds the lease.

    Atomic (unique tmp + os.replace, tmp unlinked on failure): a SIGKILL
    mid-write must not destroy the only durable copy, and a concurrent
    periodic + shutdown checkpoint must not race on a shared tmp path.
    Fenced: with an elector, the file write runs inside the lease's
    critical section only while the on-disk record still names us WITH
    THE SAME fencing token the snapshot was serialized under — a deposed
    leader resuming from a stall cannot clobber the new leader's newer
    checkpoint, even if it re-acquired the lease in the meantime (its
    token changed, so the pre-deposition snapshot is refused).
    Serialization happens OUTSIDE the flock (under the server lock
    alone): holding the shared-volume lock for a multi-second
    50k-workload dump would stall every replica's election tick.
    A process-local sequence (srv._ckpt_seq/_ckpt_written) additionally
    orders concurrent checkpoints in the SAME process, so a stalled
    periodic dump can never replace a newer shutdown dump."""
    from kueue_tpu import serialization as ser
    from kueue_tpu.utils.lease import atomic_write_text

    with srv.lock:
        state = ser.runtime_to_state(srv.runtime)
        snap_token = srv.elector.lease.token if srv.elector else None
        # stamp the serialization-time token into the checkpoint: the
        # recovery replay refuses journal records with OLDER tokens (a
        # deposed leader's stray appends)
        state["persistence"]["token"] = snap_token
        # the journal prefix this checkpoint covers — safe to compact
        # once the checkpoint is durably on disk
        snap_journal_seq = state["persistence"]["journalSeq"]
        journal = getattr(srv.runtime, "journal", None)
        text = json.dumps(state, indent=1)
        srv._ckpt_seq += 1
        seq = srv._ckpt_seq

    def _write_if_newest() -> bool:
        if seq <= srv._ckpt_written:
            return False  # a newer snapshot already landed
        if journal is not None:
            # records up to snap_journal_seq must be durable BEFORE the
            # checkpoint that compacts them away claims to cover them
            try:
                journal.sync()
            except OSError:
                pass  # degraded journal: the checkpoint still lands
        atomic_write_text(
            state_path, text, ".state-", fault_point="checkpoint.mid_write"
        )
        srv._ckpt_written = seq
        if journal is not None:
            # the checkpoint IS the compaction point
            journal.compact(snap_journal_seq)
        return True

    if srv.elector is None:
        with srv._ckpt_write_lock:
            return _write_if_newest()
    lease = srv.elector.lease
    with lease._locked():
        if not lease.is_held() or lease.token != snap_token:
            # deposed since the snapshot was taken (even if we lead
            # again under a new token): the snapshot is stale
            return False
        with srv._ckpt_write_lock:
            return _write_if_newest()


def fenced_delta_checkpoint(srv) -> bool:
    """``fenced_checkpoint`` for the delta-chain shape (--state-dir):
    same two-phase choreography — serialize under the server lock
    (``DeltaCheckpointer.prepare``: journal mark + O(changed) delta or
    periodic full anchor), then the durable write + compaction + chain
    GC (``commit``) inside the lease's critical section only while the
    on-disk record still names us with the snapshot's token. The
    process-local ``_ckpt_seq``/``_ckpt_written`` ordering holds too: a
    stalled periodic prepare can never commit over a newer shutdown
    one (its marks were never cleared, so nothing is lost by the
    abandon)."""
    ckpt = getattr(srv.runtime, "checkpointer", None)
    if ckpt is None:
        return False
    with srv.lock:
        snap_token = srv.elector.lease.token if srv.elector else None
        prep = ckpt.prepare(srv.runtime, token=snap_token)
        srv._ckpt_seq += 1
        seq = srv._ckpt_seq

    def _write_if_newest() -> bool:
        if seq <= srv._ckpt_written:
            ckpt.abandon(prep)
            return False  # a newer snapshot already landed
        ok = ckpt.commit(prep)
        if ok:
            srv._ckpt_written = seq
        return ok

    if srv.elector is None:
        with srv._ckpt_write_lock:
            return _write_if_newest()
    lease = srv.elector.lease
    with lease._locked():
        if not lease.is_held() or lease.token != snap_token:
            # deposed since the snapshot was taken: the snapshot is
            # stale (and its dirty marks survive for the next leader
            # tenure's checkpoint)
            ckpt.abandon(prep)
            return False
        with srv._ckpt_write_lock:
            return _write_if_newest()


def promote_reload(srv, state_path: str, build_runtime,
                   run_reconcile: bool = True,
                   require_standby: bool = False,
                   journal_path: str = "",
                   journal_opts: dict = None) -> bool:
    """On lease takeover, REBUILD srv.runtime from the old leader's
    latest checkpoint — not an upsert into the standby's stale store,
    which would resurrect objects the old leader deleted. Data loss is
    bounded by the checkpoint period — or, with ``journal_path``, by
    the journal fsync window: promotion then runs full recovery
    (checkpoint + replay of newer records, stale fencing tokens
    refused, invariants checked) and attaches the journal to the new
    runtime. Returns True when a checkpoint was loaded (or, with a
    journal, when anything was recovered).

    Also used for the standby read-refresh with ``run_reconcile=False``
    + ``require_standby=True``: a standby mirrors the leader's
    checkpoint verbatim and must NOT run scheduling cycles of its own
    — nor open the journal for append (that would truncate/extend a
    file the leader is writing); standby refreshes stay
    checkpoint-only. And if this replica was promoted while the (slow)
    mirror rebuild was in flight, the swap is abandoned — installing a
    never-reconciled pre-promotion mirror over the new leader's live
    runtime would discard writes accepted since promotion."""
    from kueue_tpu import serialization as ser

    journal = None
    if journal_path and not require_standby:
        from kueue_tpu.storage import recover

        fresh = build_runtime()
        res = recover(state_path, journal_path, runtime=fresh, strict=True,
                      **(journal_opts or {}))
        journal = res.journal
        loaded = res.checkpoint_loaded or res.replayed > 0
        if not loaded:
            journal.close()
            return False
    else:
        from kueue_tpu.storage import load_state_any

        if not (state_path and os.path.exists(state_path)):
            return False
        # load_state_any reads both checkpoint shapes: a full-dump FILE
        # or a delta-chain DIRECTORY (--state-dir)
        data = load_state_any(state_path)
        if data is None:
            return False
        fresh = build_runtime()
        ser.runtime_from_state(data, runtime=fresh)
    with srv.lock:
        if require_standby and srv.elector is not None and srv.elector.is_leader:
            return False
        if journal is not None:
            journal.token_provider = (
                (lambda: srv.elector.lease.token) if srv.elector else None
            )
            fresh.attach_journal(journal)
            old_journal = getattr(srv.runtime, "journal", None)
            if old_journal is not None and old_journal is not journal:
                old_journal.close()
        srv.runtime = fresh
        if run_reconcile:
            fresh.run_until_idle()
    return True


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kueue_tpu.server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8082)
    parser.add_argument(
        "--config",
        help="manager configuration file (kueue_tpu.config schema, "
        "the --config of cmd/kueue/main.go)",
    )
    parser.add_argument(
        "--state",
        help="JSON state file (CLI wire format): loaded at startup if "
        "present, written back on shutdown — the durable checkpoint "
        "active-passive recovery restarts from",
    )
    parser.add_argument(
        "--journal",
        help="directory for the write-ahead admission journal: every "
        "state mutation is appended as a CRC-framed record; startup "
        "(and promotion) recovers from the newest checkpoint plus "
        "replay of newer records, bounding crash data loss to the "
        "fsync window instead of the checkpoint period",
    )
    parser.add_argument(
        "--journal-fsync", choices=["always", "interval", "never"],
        default="interval",
        help="journal durability policy: always = fsync every append "
        "(power-loss-safe, slow), interval = fsync at most every "
        "--journal-fsync-interval seconds (the default), never = "
        "leave it to the OS",
    )
    parser.add_argument(
        "--journal-fsync-interval", type=float, default=0.05,
        help="seconds between journal fsyncs under --journal-fsync "
        "interval (the bounded power-loss window)",
    )
    parser.add_argument(
        "--journal-segment-bytes", type=int, default=8 * 1024 * 1024,
        help="rotate journal segments at this size; checkpoints delete "
        "fully-covered segments (compaction)",
    )
    parser.add_argument(
        "--state-dir",
        help="directory for DELTA checkpoints (requires --journal, "
        "replaces --state): periodic checkpoints record only objects "
        "changed since the previous one, chained back to a full "
        "anchor every --checkpoint-anchor-every checkpoints — "
        "compaction cost is O(changed) instead of O(live workloads). "
        "Recovery loads anchor + delta chain + journal suffix; "
        "`kueuectl state verify` walks the chain — see deploy/README "
        "'Sustained operation'",
    )
    parser.add_argument(
        "--checkpoint-anchor-every", type=int, default=16,
        help="write a full anchor checkpoint after this many deltas "
        "(bounds chain length and recovery walk; --state-dir only)",
    )
    parser.add_argument(
        "--checkpoint-retain", type=int, default=1,
        help="checkpoint chains (anchor + its deltas) to keep on disk; "
        "older chains are garbage-collected after each successful "
        "checkpoint (--state-dir only)",
    )
    parser.add_argument(
        "--no-solver", action="store_true",
        help="disable the batched TPU nomination path",
    )
    parser.add_argument(
        "--solver-path", choices=["auto", "host", "device"], default="auto",
        help="solver guard mode (core/guard.py): auto = device with "
        "circuit-breaker failover to the numpy host mirror (the "
        "default), host = force the host mirror (degraded-solver "
        "runbook escape hatch), device = never fail over (debugging; "
        "device faults propagate)",
    )
    parser.add_argument(
        "--policy", default="first-fit",
        help="admission policy (kueue_tpu/policy closed registry): "
        "first-fit = the score-free default (bit-for-bit the "
        "pre-policy decisions), gavel = heterogeneity-aware flavor "
        "scoring from kueue.tpu/throughput-<flavor> labels, prema = "
        "predictive victim ordering from kueue.tpu/remaining-seconds, "
        "deadline = SLO-boosted nomination from kueue.tpu/deadline, "
        "gavel-deadline = both. What-if a switch first: kueuectl plan "
        "with a {\"kind\": \"policy\"} scenario delta",
    )
    parser.add_argument(
        "--pipeline", choices=["on", "serial", "off"], default="on",
        help="double-buffered bulk-drain loop (core/pipeline.py): on = "
        "chunked drain rounds with the next round's encode+solve "
        "prefetched on a speculative snapshot while the host applies "
        "the current one (the default; overlap observable via "
        "kueue_pipeline_* metrics and /debug/cycles spans), serial = "
        "the same chunked rounds without prefetch (A/B baseline), "
        "off = the pre-pipeline single-dispatch drain",
    )
    parser.add_argument(
        "--pipeline-chunk-cycles", type=int, default=16,
        help="kernel cycles per pipelined drain round: smaller chunks "
        "overlap sooner but pay more dispatch round trips",
    )
    parser.add_argument(
        "--megaloop", default="off", metavar="on|off|K",
        help="device-resident admission megaloop "
        "(ops/megaloop_kernel): fuse up to K drain rounds of "
        "--pipeline-chunk-cycles kernel cycles each into ONE device "
        "dispatch — the host journals/applies the batched "
        "round-stamped decision log trailing the device, each round "
        "validated by the pipeline's conflict-check contract (any "
        "mismatch truncates the batch and re-solves from the real "
        "state). on = K tuned online per backlog mix, an integer "
        "pins K, off = per-round launches (the default). Composes "
        "with --pipeline (on also prefetches the next fused launch) "
        "and --mesh; observable via kueue_megaloop_* metrics — see "
        "deploy/README 'Megaloop'",
    )
    parser.add_argument(
        "--mesh", default="off", metavar="auto|N|off",
        help="multi-chip admission (kueue_tpu/parallel): shard every "
        "drain-family device launch over a (wl[, fr]) device mesh — "
        "auto = all local devices, N = the first N, off = "
        "single-device (the default). Composes with --pipeline "
        "(prefetched launches ride the same sharded path) and with "
        "the solver guard (host mirrors are mesh-agnostic). Falls "
        "back to single-device when fewer than 2 devices resolve; "
        "multi-host meshes need jax.distributed.initialize() before "
        "startup (deploy/README 'Multi-chip admission')",
    )
    parser.add_argument(
        "--panel-widths", default=None, metavar="W1,W2",
        help="fixed victim-search panel-width schedule for the "
        "contended drain (e.g. '16,64': narrow cost-ordered panel "
        "first, escalate to the exact wide panel only on an "
        "inconclusive truncated search). Default: the online "
        "per-workload-mix PanelTuner picks the narrow width",
    )
    parser.add_argument(
        "--no-auto-reconcile", action="store_true",
        help="only reconcile on POST /reconcile",
    )
    parser.add_argument(
        "--gateway", choices=["on", "off"], default="off",
        help="write-path gateway (kueue_tpu/gateway): coalesce "
        "concurrent workload POSTs (and batch sections) into one "
        "serving-lock critical section, one group-committed journal "
        "sync and one admission pass per flush window, with "
        "per-tenant token-bucket load-shedding (429 + Retry-After). "
        "The serving-at-scale ingest path — see deploy/README "
        "'Serving at scale'",
    )
    parser.add_argument(
        "--gateway-flush-interval", type=float, default=0.005,
        help="seconds one gateway flush window coalesces arrivals for "
        "(smaller = lower added latency, less batching)",
    )
    parser.add_argument(
        "--gateway-max-batch", type=int, default=256,
        help="most requests one gateway flush applies in one critical "
        "section",
    )
    parser.add_argument(
        "--gateway-queue-depth", type=int, default=4096,
        help="bounded coalescing-queue capacity; arrivals beyond it "
        "are shed with 429",
    )
    parser.add_argument(
        "--gateway-tenant-rate", type=float, default=0.0,
        help="per-tenant (LocalQueue/namespace) sustained write budget "
        "in requests/s; 0 disables the rate limiter (queue-capacity "
        "shedding still applies)",
    )
    parser.add_argument(
        "--gateway-tenant-burst", type=float, default=0.0,
        help="per-tenant token-bucket burst (default: 2x the rate)",
    )
    parser.add_argument(
        "--slo-target-p95", type=float, default=0.0,
        help="default queue-to-admission p95 target in seconds for "
        "every ClusterQueue (kueue_slo_* family; 0 disables SLO "
        "tracking unless --slo-target sets per-CQ targets)",
    )
    parser.add_argument(
        "--slo-target", action="append", default=None, metavar="CQ=SECONDS",
        help="per-ClusterQueue queue-to-admission p95 target "
        "(repeatable; overrides --slo-target-p95 for that CQ)",
    )
    parser.add_argument(
        "--slo-objective", type=float, default=0.95,
        help="fraction of admissions that must land within the target "
        "(the error budget is 1 - objective)",
    )
    parser.add_argument(
        "--slo-burn-window", type=float, default=300.0,
        help="sliding window (seconds) the error-budget burn rate is "
        "computed over",
    )
    parser.add_argument(
        "--slo-burn-threshold", type=float, default=2.0,
        help="burn rate above which the budget is burning too fast; "
        "sustained for --slo-sustain seconds flips /healthz to "
        "'degraded' and kueue_slo_degraded to 1",
    )
    parser.add_argument(
        "--slo-sustain", type=float, default=60.0,
        help="seconds the burn threshold must be continuously exceeded "
        "before the SLO reports degraded",
    )
    parser.add_argument(
        "--replica-of", metavar="URL",
        help="run as a journal-tailing READ REPLICA of the leader at "
        "URL (a kueue_tpu.server started with --journal): the leader's "
        "replication feed is polled and replayed into a live read-only "
        "runtime serving watch/SSE, visibility, explain and "
        "best-effort-stale plan; mutating requests 307-redirect to the "
        "leader. Staleness is reported on /healthz and "
        "kueue_replica_{applied_seq,lag_seconds}",
    )
    parser.add_argument(
        "--replica-poll-interval", type=float, default=0.5,
        help="seconds between replication-feed polls in --replica-of "
        "mode (the staleness floor)",
    )
    parser.add_argument(
        "--replica-id",
        help="this replica's identity in the leader's roster "
        "(default: hostname-pid)",
    )
    parser.add_argument(
        "--replica-token", default=None,
        help="bearer token presented to a --replica-of leader started "
        "with --auth-token (default: --auth-token, so one shared "
        "token secures both directions)",
    )
    parser.add_argument(
        "--federation-worker", action="append", default=None,
        metavar="NAME=URL",
        help="run this control plane as a MultiKueue federation manager "
        "dispatching to the named worker control plane (repeatable; "
        "URL is another kueue_tpu.server). Pending workloads mirror to "
        "the planner-ranked workers, admit wherever quota clears "
        "first, and losers are retracted through the journaled "
        "at-least-once retraction protocol",
    )
    parser.add_argument(
        "--federation-worker-token", default=None,
        help="bearer token presented to --federation-worker servers "
        "started with --auth-token",
    )
    parser.add_argument(
        "--federation-lost-timeout", type=float, default=900.0,
        help="seconds a partitioned worker may hold a workload's "
        "reservation before the dispatcher deposes it (fence bump + "
        "re-dispatch; the multiKueue.workerLostTimeout analog)",
    )
    parser.add_argument(
        "--federation-fanout", type=int, default=None,
        help="mirror each workload to at most this many best-ranked "
        "workers (default: all configured workers)",
    )
    parser.add_argument(
        "--global-scheduler", choices=["on", "off"], default="off",
        help="run the federation-wide global scheduler on this manager "
        "(requires --federation-worker): aggregate every worker's "
        "pending positions / fair-share standings / capacities by "
        "tailing the replica feed each worker already serves, rescore "
        "all (pending workload x cluster) pairs in one batched kernel "
        "pass every --global-rescore-interval, and retract-and-"
        "redispatch placements another cluster beats by more than "
        "--global-hysteresis (journaled, fenced — exactly-one "
        "admission preserved). Served at GET /global/standings and "
        "`kueuectl pending-workloads --global`",
    )
    parser.add_argument(
        "--global-hysteresis", type=float, default=60.0,
        help="seconds of forecast gain another cluster must offer "
        "before a placement is rebalanced (churn guard)",
    )
    parser.add_argument(
        "--global-rescore-interval", type=float, default=30.0,
        help="seconds between global rescore passes",
    )
    parser.add_argument(
        "--elastic", choices=["on", "off"], default="off",
        help="run the elastic capacity plane: pending "
        "ProvisioningRequests are ranked by scoring each candidate "
        "flavor scale-up through one batched planner launch, the "
        "winner is submitted to --capacity-provider, and on "
        "Provisioned a journaled elastic_grant raises the flavor's "
        "nominal quota (BookingExpired/CapacityRevoked shrink it back "
        "via elastic_revoke). Served at GET /capacity and `kueuectl "
        "capacity`",
    )
    parser.add_argument(
        "--capacity-provider", choices=["simulated"], default="simulated",
        help="capacity provider backing --elastic (simulated: clock-"
        "driven in-process autoscaler with --elastic-provision-delay)",
    )
    parser.add_argument(
        "--elastic-provision-delay", type=float, default=5.0,
        help="seconds the simulated capacity provider takes to "
        "provision an accepted request",
    )
    parser.add_argument(
        "--elastic-capacity-limit", action="append", default=None,
        metavar="FLAVOR:RESOURCE=AMOUNT",
        help="cap the simulated provider's total grantable capacity "
        "for a (flavor, resource) pair (repeatable; default: "
        "unlimited) — requests past the cap fail and walk the "
        "b*2^(n-1) retry ladder",
    )
    parser.add_argument(
        "--leader-elect-lease",
        help="path to a shared lease file (on the state volume): "
        "enables leader election — the holder accepts writes and "
        "schedules, standbys serve reads and take over on lapse "
        "(the LeaderElection analog of cmd/kueue/main.go)",
    )
    parser.add_argument(
        "--leader-elect-identity",
        help="this replica's identity in the lease "
        "(default: hostname-pid)",
    )
    parser.add_argument(
        "--leader-elect-lease-duration", type=float, default=15.0,
        help="seconds a lapsed lease stays unclaimable before takeover",
    )
    parser.add_argument(
        "--state-checkpoint-period", type=float, default=30.0,
        help="seconds between periodic --state checkpoints while "
        "leading (bounds data loss on SIGKILL; 0 disables)",
    )
    parser.add_argument(
        "--trace-capacity", type=int, default=4096,
        help="traces kept in the in-memory distributed-tracing store "
        "(LRU-bounded; served at /debug/traces, exported by `kueuectl "
        "trace`, replicated to read replicas on the journal feed). "
        "0 disables tracing entirely",
    )
    parser.add_argument(
        "--auth-token",
        default=os.environ.get("KUEUE_AUTH_TOKEN") or None,
        help="bearer token gating mutating routes, metrics, state and "
        "debug (the secured-endpoint analog of cmd/kueue/main.go "
        "authn/z; default: $KUEUE_AUTH_TOKEN, unset = open)",
    )
    parser.add_argument(
        "--tls-cert-dir",
        help="serve TLS with self-managed certs in this directory "
        "(ca.crt/tls.crt/tls.key generated and rotated before expiry "
        "— the internalCertManagement analog of pkg/util/cert; "
        "clients verify against ca.crt)",
    )
    parser.add_argument(
        "--tls-cert",
        help="serve TLS with a provided certificate (PEM path; pair "
        "with --tls-key — the provided-certificates mode of "
        "cmd/kueue/main.go:161-168)",
    )
    parser.add_argument("--tls-key", help="private key for --tls-cert")
    parser.add_argument(
        "--tls-dns-name", action="append", default=None,
        help="SAN for self-managed certs (repeatable; default: "
        "--host + localhost + 127.0.0.1)",
    )
    args = parser.parse_args(argv)
    if args.state_dir and not args.journal:
        parser.error("--state-dir requires --journal (deltas chain over "
                     "the journal's sequence numbers)")
    if args.state_dir and args.state:
        parser.error("--state-dir and --state are mutually exclusive")
    if bool(args.tls_cert) != bool(args.tls_key):
        parser.error("--tls-cert and --tls-key must be given together")
    if args.tls_cert_dir and args.tls_cert:
        parser.error(
            "--tls-cert-dir (self-managed) and --tls-cert (provided) "
            "are mutually exclusive"
        )
    if args.replica_of:
        # a replica never writes: it neither journals (single-writer
        # log), contends for the lease, dispatches federation work,
        # nor batches writes (it 307s them to the leader)
        for flag, val in (
            ("--journal", args.journal),
            ("--state", args.state),
            ("--state-dir", args.state_dir),
            ("--leader-elect-lease", args.leader_elect_lease),
            ("--federation-worker", args.federation_worker),
            ("--gateway", args.gateway if args.gateway == "on" else None),
            ("--elastic", args.elastic if args.elastic == "on" else None),
        ):
            if val:
                parser.error(f"--replica-of is incompatible with {flag}")
    slo_targets = {}
    for spec in args.slo_target or []:
        cq, sep, seconds = spec.partition("=")
        if not sep or not cq:
            parser.error(f"--slo-target must be CQ=SECONDS, got {spec!r}")
        try:
            slo_targets[cq] = float(seconds)
        except ValueError:
            parser.error(f"--slo-target must be CQ=SECONDS, got {spec!r}")

    from kueue_tpu import serialization as ser
    from kueue_tpu.server import KueueServer

    use_solver = False if args.no_solver else None

    if args.panel_widths:
        from kueue_tpu.core import drain as _drain_mod

        _drain_mod.set_default_panel_widths(
            tuple(int(w) for w in args.panel_widths.split(","))
        )

    mesh = None
    if args.mesh and args.mesh != "off":
        from kueue_tpu.parallel import mesh_shape_str, resolve_mesh

        mesh = resolve_mesh(args.mesh)
        if mesh is None:
            print(
                f"--mesh {args.mesh}: fewer than 2 devices resolve; "
                "running single-device",
                flush=True,
            )
        else:
            print(
                f"multi-chip admission: mesh {mesh_shape_str(mesh)} over "
                f"{mesh.size} devices",
                flush=True,
            )

    def build_runtime():
        """Construct a runtime exactly the way startup does — also used
        to REBUILD on promotion, so a promoted standby starts from the
        checkpoint alone instead of merging it into a stale store."""
        from kueue_tpu.tas import TASCache

        if args.config:
            import yaml

            from kueue_tpu.config import load_config, runtime_from_config

            with open(args.config) as f:
                cfg = load_config(yaml.safe_load(f))
            rt = runtime_from_config(cfg, tas_cache=TASCache())
            if use_solver is not None:
                rt.scheduler.use_solver = use_solver
            if args.solver_path != "auto":
                rt.guard.config.mode = args.solver_path
            rt.drain_pipeline = args.pipeline
            rt.pipeline_chunk_cycles = max(1, args.pipeline_chunk_cycles)
            rt.set_megaloop(args.megaloop)
            rt.set_mesh(mesh)
            if args.policy != "first-fit":
                rt.set_policy(args.policy, journal=False)
            _apply_trace_capacity(rt)
            _apply_slo(rt)
            return rt
        from kueue_tpu.controllers import ClusterRuntime

        rt = ClusterRuntime(
            use_solver=use_solver, tas_cache=TASCache(),
            solver_path=args.solver_path,
            drain_pipeline=args.pipeline,
            pipeline_chunk_cycles=args.pipeline_chunk_cycles,
            drain_megaloop=args.megaloop,
            mesh=mesh,
            policy=args.policy,
        )
        _apply_trace_capacity(rt)
        _apply_slo(rt)
        return rt

    def _apply_trace_capacity(rt):
        if args.trace_capacity <= 0:
            rt.tracer.enabled = False
        else:
            rt.tracer.max_traces = args.trace_capacity

    def _apply_slo(rt):
        slo = getattr(rt, "slo", None)
        if slo is None:
            return
        slo.configure(
            default_target_s=args.slo_target_p95,
            targets=slo_targets,
            objective=args.slo_objective,
            burn_window_s=args.slo_burn_window,
            burn_threshold=args.slo_burn_threshold,
            sustain_s=args.slo_sustain,
        )

    journal_opts = {
        "fsync_policy": args.journal_fsync,
        "fsync_interval_s": args.journal_fsync_interval,
        "segment_max_bytes": args.journal_segment_bytes,
    }
    # the durable-state anchor this process recovers from and
    # checkpoints to: a delta-chain directory or the classic full dump
    state_ref = args.state_dir or args.state
    runtime = build_runtime()
    journal = None
    checkpointer = None
    if args.journal:
        from kueue_tpu.storage import recover

        # crash recovery: checkpoint + replay of newer journal records
        # (torn tail truncated, stale fencing tokens refused), then the
        # invariant check — a violating state must not serve
        res = recover(
            state_ref, args.journal, runtime=runtime, strict=True,
            **journal_opts,
        )
        journal = res.journal
        print(f"journal recovery: {res.summary()}", flush=True)
        if args.state_dir:
            from kueue_tpu.storage import DeltaCheckpointer

            checkpointer = DeltaCheckpointer(
                args.state_dir,
                anchor_every=args.checkpoint_anchor_every,
                retain_chains=args.checkpoint_retain,
            ).open()
    elif args.state and os.path.exists(args.state):
        with open(args.state) as f:
            ser.runtime_from_state(json.load(f), runtime=runtime)
    srv = None  # assigned below; the callbacks close over it
    # last_token: the fencing token of our last tenure. A promotion
    # only reloads the checkpoint when the token moved — i.e. another
    # holder (or an unknown intermediary) intervened. Re-acquiring our
    # own still-valid lease after a transient renewal failure keeps the
    # token, so a lease flap must NOT roll the runtime back to a
    # checkpoint that predates writes we acknowledged. "boot" marks the
    # initial synchronous tick in srv.start(): we just loaded the same
    # checkpoint in main(), so reloading it again is pure waste.
    ha = {"last_token": None, "boot": True}

    def checkpoint() -> bool:
        if args.state_dir:
            return fenced_delta_checkpoint(srv)
        if not args.state:
            return True
        return fenced_checkpoint(srv, args.state)

    def on_promoted() -> None:
        tok = elector.lease.token
        first = ha["boot"]  # cleared in main() right after srv.start()
        resumed = ha["last_token"] is not None and ha["last_token"] == tok
        if first or resumed:
            ha["last_token"] = tok
            return
        # Record the token only AFTER a successful reload: if the
        # reload raises (transient volume error), tick() leaves us
        # non-leader and the NEXT promotion attempt must not classify
        # itself as a resume and skip the reload — that would lead with
        # the stale pre-takeover runtime.
        reloaded = (state_ref or args.journal) and promote_reload(
            srv, state_ref, build_runtime,
            journal_path=args.journal or "", journal_opts=journal_opts,
        )
        if reloaded and checkpointer is not None:
            # the fresh runtime journals into a fresh tracker; the
            # chain head on disk is still ours to extend
            srv.runtime.checkpointer = checkpointer
        ha["last_token"] = tok
        if reloaded:
            print(
                "promoted to leader; rebuilt state from checkpoint"
                + (" + journal replay" if args.journal else ""),
                flush=True,
            )

    elector = None
    if args.leader_elect_lease:
        import socket

        from kueue_tpu.utils.lease import FileLease, LeaderElector

        identity = (
            args.leader_elect_identity
            or f"{socket.gethostname()}-{os.getpid()}"
        )
        elector = LeaderElector(
            FileLease(
                args.leader_elect_lease,
                identity,
                duration=args.leader_elect_lease_duration,
            ),
            on_started_leading=on_promoted,
        )
    if journal is not None:
        # attach AFTER recovery (replay must not re-journal) and after
        # the elector exists, so records carry the live fencing token
        journal.token_provider = (
            (lambda: elector.lease.token) if elector is not None else None
        )
        runtime.attach_journal(journal)
        if checkpointer is not None:
            runtime.checkpointer = checkpointer
            print(
                "delta checkpoints: chain dir "
                f"{args.state_dir} (anchor every "
                f"{args.checkpoint_anchor_every} deltas, retaining "
                f"{args.checkpoint_retain} chain(s))",
                flush=True,
            )
    if args.elastic == "on":
        # elastic capacity plane: built AFTER journal attach/recovery
        # so grants journal durably and the plane adopts any
        # elastic_grant records replay already applied (it must never
        # re-ask the provider for capacity it provably holds)
        from kueue_tpu.elastic import SimulatedProvider, attach_elastic_plane

        limits = {}
        for spec in args.elastic_capacity_limit or []:
            fr, sep, amount = spec.partition("=")
            flavor, fsep, resource = fr.partition(":")
            if not sep or not fsep or not flavor or not resource:
                parser.error(
                    "--elastic-capacity-limit must be "
                    f"FLAVOR:RESOURCE=AMOUNT, got {spec!r}"
                )
            try:
                limits.setdefault(flavor, {})[resource] = int(amount)
            except ValueError:
                parser.error(
                    "--elastic-capacity-limit must be "
                    f"FLAVOR:RESOURCE=AMOUNT, got {spec!r}"
                )
        provider = SimulatedProvider(
            clock=runtime.clock,
            provision_delay_s=args.elastic_provision_delay,
            capacity_limits=limits or None,
        )
        attach_elastic_plane(runtime, provider=provider)
        print(
            "elastic capacity plane: provider "
            f"{args.capacity_provider} (delay "
            f"{args.elastic_provision_delay:g}s"
            + (f", limits {sorted(limits)}" if limits else "")
            + ")",
            flush=True,
        )
    if args.federation_worker:
        # federation manager mode: dispatch to remote worker control
        # planes over HTTP. Built AFTER journal attach so dispatch /
        # winner / retraction records are journaled, and the dispatcher
        # adopts any federation_* records recovery replayed.
        from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
        from kueue_tpu.admissionchecks.multikueue_transport import (
            HTTPTransport,
        )
        from kueue_tpu.federation import FederationDispatcher

        workers = {}
        worker_urls = {}
        for spec in args.federation_worker:
            name, sep, url = spec.partition("=")
            if not sep or not name or not url:
                parser.error(
                    f"--federation-worker must be NAME=URL, got {spec!r}"
                )
            worker_urls[name] = url
            workers[name] = MultiKueueCluster(
                name=name,
                transport=HTTPTransport(
                    url, token=args.federation_worker_token
                ),
            )
        dispatcher = FederationDispatcher(
            runtime,
            clusters=workers,
            worker_lost_timeout=args.federation_lost_timeout,
            fanout=args.federation_fanout,
        )
        print(
            f"federation manager: dispatching to {sorted(workers)}",
            flush=True,
        )
        if args.global_scheduler == "on":
            from kueue_tpu.federation import GlobalScheduler

            gs = GlobalScheduler(
                dispatcher,
                hysteresis_s=args.global_hysteresis,
                rescore_interval_s=args.global_rescore_interval,
            )
            # read each worker through the replica feed it already
            # serves (PR-9): one JournalTailer per wire-only worker
            # keeps a live read-only twin the forecasts run against
            for name, url in worker_urls.items():
                gs.attach_feed_reader(
                    name, url, token=args.federation_worker_token
                )
            print(
                "global scheduler: rescoring every "
                f"{args.global_rescore_interval:.0f}s, hysteresis "
                f"{args.global_hysteresis:.0f}s",
                flush=True,
            )
    elif args.global_scheduler == "on":
        parser.error("--global-scheduler requires --federation-worker")
    replica = None
    if args.replica_of:
        import socket

        from kueue_tpu.replica import ReadReplica

        replica = ReadReplica(
            args.replica_of,
            token=args.replica_token or args.auth_token,
            replica_id=(
                args.replica_id or f"{socket.gethostname()}-{os.getpid()}"
            ),
            build_runtime=build_runtime,
            poll_interval_s=args.replica_poll_interval,
        )
    tls = None
    if args.tls_cert_dir:
        from kueue_tpu.utils.cert import CertRotator

        sans = args.tls_dns_name or ["localhost", "127.0.0.1", args.host]
        # dedupe, keep order (the host may already be a default SAN)
        tls = CertRotator(args.tls_cert_dir, dns_names=list(dict.fromkeys(sans)))
    elif args.tls_cert:
        tls = (args.tls_cert, args.tls_key)
    gateway = None
    if args.gateway == "on":
        from kueue_tpu.gateway import TenantLimiter, WriteGateway

        limiter = None
        if args.gateway_tenant_rate > 0:
            limiter = TenantLimiter(
                args.gateway_tenant_rate,
                burst=args.gateway_tenant_burst or None,
            )
        gateway = WriteGateway(
            flush_interval_s=args.gateway_flush_interval,
            max_batch=args.gateway_max_batch,
            max_queue=args.gateway_queue_depth,
            limiter=limiter,
        )
        print(
            "gateway: coalescing writes "
            f"(flush window {args.gateway_flush_interval * 1e3:.1f} ms, "
            f"queue {args.gateway_queue_depth}, tenant rate "
            f"{args.gateway_tenant_rate or 'unlimited'}/s)",
            flush=True,
        )
    srv = KueueServer(
        runtime=runtime,
        host=args.host,
        port=args.port,
        auto_reconcile=not args.no_auto_reconcile,
        elector=elector,
        auth_token=args.auth_token,
        tls=tls,
        replica=replica,
        gateway=gateway,
    )
    port = srv.start()
    if replica is not None:
        # anchor on the leader's checkpoint (best-effort — an
        # unreachable leader leaves an empty replica retrying) and
        # start the tail loop
        replica.start()
    ha["boot"] = False  # any later promotion is a real takeover
    role = ""
    if elector is not None:
        role = " as leader" if elector.is_leader else " as standby"
    elif replica is not None:
        role = f" as read replica of {args.replica_of}"
    scheme = "https" if tls is not None else "http"
    print(
        f"kueue-tpu server listening on {scheme}://{args.host}:{port}{role}",
        flush=True,
    )
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    if hasattr(signal, "SIGUSR2"):
        # the pkg/debugger analog: kill -USR2 <pid> dumps queues,
        # cache, recent cycles, persistence/solver/replication posture
        # to stderr. Reads srv.runtime at SIGNAL time, so it follows
        # promotion/replica runtime swaps.
        from kueue_tpu import debugger

        signal.signal(
            signal.SIGUSR2,
            lambda *_: sys.stderr.write(debugger.dump(srv.runtime) + "\n"),
        )

    if args.elastic == "on" or args.federation_worker:
        # the elastic capacity loop is TIME-driven (provider delays,
        # retry backoffs) and drain-ahead scale-down re-dispatches
        # deposed placements from federation.step(): both only make
        # progress inside run_until_idle, which otherwise fires only on
        # API traffic — an in-flight grant or a drained placement could
        # sit forever on an idle server without this ticker
        tick = 1.0
        if args.elastic == "on":
            tick = max(0.2, min(2.0, args.elastic_provision_delay / 2))

        def _reconcile_loop():
            while not stop.wait(tick):
                try:
                    if elector is not None and not elector.is_leader:
                        continue
                    with srv.lock:
                        srv.runtime.run_until_idle()
                except Exception as e:  # noqa: BLE001 — a provider or
                    # worker hiccup must not kill the capacity loop for
                    # the rest of the process lifetime
                    print(f"background reconcile failed: {e!r}", flush=True)

        threading.Thread(target=_reconcile_loop, daemon=True).start()

    ckpt_thread = None
    if state_ref and args.state_checkpoint_period > 0:
        # Periodic leader checkpoints bound the data lost to a SIGKILL
        # (and are what a promoted standby reloads). Standbys never
        # checkpoint — on a shared state volume that would clobber the
        # leader's durable copy with a stale one — but they DO reload
        # each new checkpoint so their read endpoints (visibility,
        # metrics, dashboard, GETs) track the leader instead of serving
        # boot-time state forever.
        # start from the checkpoint main() already loaded: the first
        # standby iteration must not rebuild identical state
        # (a chain DIRECTORY's mtime moves when a checkpoint file lands
        # or is GC'd, so the standby refresh check works for both)
        reloaded_mtime = [
            os.path.getmtime(state_ref) if os.path.exists(state_ref) else 0.0
        ]

        def _ckpt_loop():
            while not stop.wait(args.state_checkpoint_period):
                try:
                    if elector is None or elector.is_leader:
                        checkpoint()
                    elif os.path.exists(state_ref):
                        mtime = os.path.getmtime(state_ref)
                        if mtime > reloaded_mtime[0]:
                            promote_reload(srv, state_ref, build_runtime,
                                           run_reconcile=False,
                                           require_standby=True)
                            reloaded_mtime[0] = mtime
                except Exception as e:  # noqa: BLE001 — any failure
                    # (volume error, serialization bug) must not
                    # silently kill periodic durability for the
                    # rest of the process lifetime
                    print(f"checkpoint failed: {e!r}", flush=True)

        ckpt_thread = threading.Thread(target=_ckpt_loop, daemon=True)
        ckpt_thread.start()

    stop.wait()
    was_leader = elector is None or elector.is_leader
    # write-safe shutdown: requests drain, THEN the final checkpoint
    # lands, THEN the lease is released — a standby promoted by the
    # release reloads a checkpoint that includes every accepted write
    final = {"saved": False}

    def _final_checkpoint() -> None:
        final["saved"] = checkpoint()

    if replica is not None:
        replica.stop()
    srv.stop(before_release=_final_checkpoint if was_leader else None)
    if ckpt_thread is not None:
        ckpt_thread.join(timeout=5)
    live_journal = getattr(srv.runtime, "journal", None)
    if live_journal is not None:
        live_journal.close()  # final fsync of any unsynced tail
    if state_ref and was_leader:
        if final["saved"]:
            print(f"state saved to {state_ref}", flush=True)
        else:
            # the fence refused the write: the lease lapsed during
            # drain and another replica owns the state file now
            print(
                f"final checkpoint SKIPPED (lease no longer held); "
                f"latest state lives with the current leader",
                flush=True,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
