"""Typed HTTP client for KueueServer (the client-go analog).

Thin urllib wrapper; every method mirrors one server route. Used by
the CLI's --server mode and by round-trip tests.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from typing import Optional


class ClientError(Exception):
    def __init__(self, status: int, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        # gateway load-shedding (429): the server's Retry-After hint,
        # surfaced after the client's own capped backoff gave up
        self.retry_after_s = retry_after_s


class KueueClient:
    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        insecure: bool = False,
        max_429_retries: int = 4,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 5.0,
        backoff_jitter: float = 0.1,
        sleep_fn=time.sleep,
        rng: Optional[random.Random] = None,
    ):
        """``ca_cert``: path to a CA bundle that must have signed the
        server's cert (the kubeconfig certificate-authority analog for
        an https:// base_url). ``insecure``: skip verification (the
        kubeconfig insecure-skip-tls-verify analog, tests only).

        429 handling: a shed write (the gateway's per-tenant
        backpressure) is retried up to ``max_429_retries`` times,
        honoring the server's Retry-After capped at ``backoff_cap_s``
        (falling back to ``backoff_base_s * 2^(n-1)``), with the
        RemoteClient's multiplicative jitter pattern — delay scaled by
        [1, 1 + jitter) — so a fleet of shed writers does not re-slam
        the gateway in lockstep. ``max_429_retries=0`` surfaces the 429
        immediately."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token
        self.max_429_retries = max_429_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.backoff_jitter = backoff_jitter
        self._sleep = sleep_fn
        self._rng = rng or random.Random()
        # cumulative 429s observed (retried or surfaced) — bench load
        # generators read this to report client-side shed pressure
        self.throttled_total = 0
        # replica awareness, refreshed per request: read replicas label
        # every response with X-Kueue-Role/X-Kueue-Replica-Lag, and
        # mutating verbs they 307-redirect are re-issued at the leader
        # (urllib refuses to follow 307 with a body on its own)
        self.last_role: Optional[str] = None
        self.last_replica_lag_s: Optional[float] = None
        self.last_redirected_to: Optional[str] = None
        # W3C trace-context propagation: when set, every request
        # carries it as the ``traceparent`` header (workload upserts at
        # the server join the caller's trace; the replication feed
        # annotates the replica roster with it)
        self.traceparent: Optional[str] = None
        self._ssl_context = None
        if base_url.startswith("https"):
            import ssl

            if insecure:
                # public-API spelling of an unverified context (the
                # private ssl._create_unverified_context helper is not
                # a stable interface)
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
                self._ssl_context = ctx
            else:
                self._ssl_context = ssl.create_default_context(cafile=ca_cert)

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 timeout_s: Optional[float] = None):
        self.last_redirected_to = None
        return self._request_url(
            f"{self.base_url}{path}", method, body, timeout_s=timeout_s
        )

    def _retry_after_delay(self, header: Optional[str], attempt: int) -> float:
        """Backoff for one shed (429) retry: the server's Retry-After
        when present, else ``base * 2^(attempt)``; capped; jittered
        multiplicatively (the RemoteClient pattern — [1, 1+j))."""
        delay = None
        if header:
            try:
                delay = float(header)
            except ValueError:
                delay = None
        if delay is None:
            delay = self.backoff_base_s * (2 ** attempt)
        delay = min(self.backoff_cap_s, max(0.0, delay))
        if self.backoff_jitter:
            delay *= 1.0 + self.backoff_jitter * self._rng.random()
        return delay

    def _request_url(self, url: str, method: str,
                     body: Optional[dict] = None, redirects: int = 1,
                     timeout_s: Optional[float] = None):
        # per-call deadline override (gray-failure adaptive deadlines):
        # callers that track the server's observed RTT — the replica
        # tailer's poll loop — pass an explicit ``timeout_s`` instead
        # of riding the constructor-wide default
        effective_timeout = timeout_s if timeout_s is not None else self.timeout
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if data else {}
        if self.token is not None:
            headers["Authorization"] = f"Bearer {self.token}"
        if self.traceparent:
            headers["traceparent"] = self.traceparent
        attempt_429 = 0
        while True:
            req = urllib.request.Request(
                url, data=data, method=method, headers=headers
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=effective_timeout, context=self._ssl_context
                ) as resp:
                    raw = resp.read()
                    ctype = resp.headers.get("Content-Type", "")
                    self._note_replica_headers(resp.headers)
            except urllib.error.HTTPError as e:
                if e.code in (307, 308) and redirects > 0:
                    # a read replica redirecting a mutating verb to its
                    # leader: urllib never re-sends a body across a
                    # redirect, so follow it ourselves — same method,
                    # same body, once (the leader does not redirect
                    # again)
                    location = e.headers.get("Location")
                    if location:
                        self.last_redirected_to = location
                        return self._request_url(
                            location, method, body, redirects=redirects - 1,
                            timeout_s=timeout_s,
                        )
                retry_after = e.headers.get("Retry-After")
                if e.code == 429:
                    # the gateway shed this write: back off (capped,
                    # jittered) and retry — federation dispatch and
                    # bench load generators must pace themselves
                    # instead of hammering a saturated gateway
                    self.throttled_total += 1
                    if attempt_429 < self.max_429_retries:
                        e.read()
                        self._sleep(
                            self._retry_after_delay(retry_after, attempt_429)
                        )
                        attempt_429 += 1
                        continue
                try:
                    message = json.loads(e.read()).get("error", str(e))
                except Exception:  # noqa: BLE001
                    message = str(e)
                retry_s = None
                if retry_after:
                    try:
                        retry_s = float(retry_after)
                    except ValueError:
                        retry_s = None
                raise ClientError(e.code, message, retry_after_s=retry_s)
            if ctype.startswith("application/json"):
                return json.loads(raw)
            return raw.decode()

    def _note_replica_headers(self, headers) -> None:
        self.last_role = headers.get("X-Kueue-Role") or "leader"
        lag = headers.get("X-Kueue-Replica-Lag")
        try:
            self.last_replica_lag_s = float(lag) if lag is not None else None
        except ValueError:
            self.last_replica_lag_s = None

    @property
    def served_by_replica(self) -> bool:
        """Did the last response come from a read replica? (Drives the
        kueuectl "(replica, lag …)" note on read commands.)"""
        return self.last_role == "replica"

    # ---- probes / metrics ----
    def healthz(self) -> dict:
        return self._request("GET", "/healthz")

    def metrics_text(self) -> str:
        return self._request("GET", "/metrics")

    # ---- objects ----
    def apply(self, section: str, obj: dict) -> dict:
        return self._request("POST", f"/apis/kueue/v1beta1/{section}", obj)

    def apply_batch(self, sections: dict) -> dict:
        """Bulk upsert {section: [objects]} in one request."""
        return self._request("POST", "/apis/kueue/v1beta1/batch", sections)

    def list(self, section: str) -> list:
        return self._request("GET", f"/apis/kueue/v1beta1/{section}")["items"]

    def get(self, section: str, name: str) -> dict:
        return self._request("GET", f"/apis/kueue/v1beta1/{section}/{name}")

    def get_workload(self, namespace: str, name: str) -> dict:
        return self._request(
            "GET", f"/apis/kueue/v1beta1/workloads/{namespace}/{name}"
        )

    def delete_workload(self, namespace: str, name: str) -> dict:
        return self._request(
            "DELETE", f"/apis/kueue/v1beta1/workloads/{namespace}/{name}"
        )

    def delete(self, section: str, name: str) -> dict:
        """Delete a cluster-scoped object (clusterqueues,
        resourceflavors, nodes)."""
        return self._request("DELETE", f"/apis/kueue/v1beta1/{section}/{name}")

    def delete_cluster_queue(self, name: str) -> dict:
        return self.delete("clusterqueues", name)

    def set_admission_check_state(
        self, namespace: str, name: str, check: str, state: str, message: str = ""
    ) -> dict:
        return self._request(
            "POST",
            f"/apis/kueue/v1beta1/workloads/{namespace}/{name}/admissionchecks",
            {"name": check, "state": state, "message": message},
        )

    # ---- visibility ----
    def pending_workloads_cq(self, cq: str, offset: int = 0, limit: int = 1000) -> dict:
        return self._request(
            "GET",
            f"/apis/visibility/v1beta1/clusterqueues/{cq}/pendingworkloads"
            f"?offset={offset}&limit={limit}",
        )

    def pending_workloads_lq(
        self, namespace: str, lq: str, offset: int = 0, limit: int = 1000
    ) -> dict:
        return self._request(
            "GET",
            f"/apis/visibility/v1beta1/namespaces/{namespace}/localqueues/{lq}"
            f"/pendingworkloads?offset={offset}&limit={limit}",
        )

    def workload_decisions(self, namespace: str, name: str) -> dict:
        """Per-workload decision audit trail (the `kueuectl explain`
        payload): {"workload": key, "items": [DecisionRecord dicts]}."""
        return self._request(
            "GET", f"/debug/workloads/{namespace}/{name}/decisions"
        )

    # ---- distributed tracing (kueue_tpu/tracing) ----
    def traces(self, limit: int = 64) -> dict:
        """Newest traces in the server's bounded store:
        {"items": [{traceId, root, spans, durationMs, attrs}]}."""
        return self._request("GET", f"/debug/traces?limit={limit}")

    def trace(self, trace_id: str) -> dict:
        """One full span tree: {"traceId": ..., "spans": [...]}."""
        return self._request("GET", f"/debug/traces/{trace_id}")

    def workload_trace(self, namespace: str, name: str) -> dict:
        """The workload's lifecycle trace plus its referenced cycle
        traces (the `kueuectl trace` payload): {"workload", "traceId",
        "spans"} — Chrome-trace exportable via tracing.to_chrome_trace."""
        return self._request(
            "GET", f"/debug/workloads/{namespace}/{name}/trace"
        )

    def plan(
        self,
        scenarios: Optional[list] = None,
        workload: Optional[str] = None,
        cluster_queue: Optional[str] = None,
        options: Optional[dict] = None,
    ) -> dict:
        """What-if capacity plan (the `kueuectl plan` payload): POST
        scenario deltas — or just a target, letting the server generate
        the candidate-fix sweep — and get back ranked per-scenario
        admission outcomes. Read-only; leader-only in HA mode."""
        body: dict = {}
        if scenarios is not None:
            body["scenarios"] = scenarios
        target = {}
        if workload:
            target["workload"] = workload
        if cluster_queue:
            target["clusterQueue"] = cluster_queue
        if target:
            body["target"] = target
        if options:
            body["options"] = options
        return self._request("POST", "/debug/plan", body)

    # ---- events / watch ----
    def events(self, resource_version: int = 0) -> dict:
        """Recorded events newer than ``resource_version`` plus the
        current head version (the relist half of list+watch)."""
        return self._request(
            "GET",
            f"/apis/kueue/v1beta1/events?resourceVersion={resource_version}",
        )

    def watch(
        self,
        section: str = "events",
        resource_version: int = 0,
        poll_timeout: float = 30.0,
    ):
        """Generator of event dicts via resourceVersion long-polls (the
        client-go Watch analog): each iteration blocks server-side until
        something newer than the last delivered resourceVersion lands —
        no client-side polling loop. On 410 (resume point fell out of
        the ring) it relists and continues from the fresh head."""
        rv = resource_version
        while True:
            try:
                out = self._request(
                    "GET",
                    f"/apis/kueue/v1beta1/{section}?watch=1"
                    f"&resourceVersion={rv}&timeoutSeconds={poll_timeout}",
                )
            except ClientError as e:
                if e.status != 410:
                    raise
                out = self.events()  # gap: relist, resume from head
            for item in out.get("items", []):
                yield item
            # follow the server's head verbatim (not max): an HA
            # promotion swaps the recorder and restarts its versions,
            # and pinning the old high-water would park this watch
            # forever
            rv = int(out.get("resourceVersion", rv))

    def stream_events(self, resource_version: int = 0):
        """Generator over the server's SSE tail (/events/stream): yields
        event dicts as the server pushes them. The read blocks on the
        live connection — delivery is server push, not polling; the
        server's keep-alive comments bound each socket read well below
        ``timeout``."""
        req = urllib.request.Request(
            f"{self.base_url}/events/stream?resourceVersion={resource_version}",
            headers=(
                {"Authorization": f"Bearer {self.token}"} if self.token else {}
            ),
        )
        resp = urllib.request.urlopen(
            req, timeout=max(self.timeout, 30.0), context=self._ssl_context
        )
        try:
            for raw in resp:
                line = raw.decode().rstrip("\n")
                if line.startswith("data: "):
                    payload = line[len("data: "):]
                    if payload and payload != "{}":
                        yield json.loads(payload)
        finally:
            resp.close()

    # ---- replication (read replicas) ----
    def journal_tail(
        self,
        since_seq: int = 0,
        since_event_rv: int = 0,
        since_audit_seq: int = 0,
        limit: int = 2048,
        replica: Optional[str] = None,
        applied_seq: Optional[int] = None,
        lag_s: Optional[float] = None,
        since_span_seq: int = 0,
        hop: Optional[int] = None,
        timeout_s: Optional[float] = None,
    ) -> dict:
        """One replication-feed poll (the JournalTailer wire): journal
        records with seq > ``since_seq`` plus event/audit/span deltas,
        and the leader's head/compaction-floor/fencing posture.
        ``replica`` + ``applied_seq``/``lag_s`` register this follower
        in the leader's roster. ``timeout_s`` overrides the client-wide
        timeout for this one poll (the HTTPTailSource adaptive
        deadline)."""
        params = [
            f"sinceSeq={since_seq}",
            f"sinceEventRv={since_event_rv}",
            f"sinceAuditSeq={since_audit_seq}",
            f"sinceSpanSeq={since_span_seq}",
            f"limit={limit}",
        ]
        if replica:
            from urllib.parse import quote

            params.append(f"replica={quote(replica)}")
            if applied_seq is not None:
                params.append(f"appliedSeq={applied_seq}")
            if lag_s is not None:
                params.append(f"lagSeconds={lag_s}")
            if hop is not None:
                params.append(f"hop={hop}")
        return self._request(
            "GET", "/apis/kueue/v1beta1/journal?" + "&".join(params),
            timeout_s=timeout_s,
        )

    def replicas(self) -> dict:
        """The follower roster (`kueuectl replicas` payload): on a
        leader, every replica that polled the feed with its staleness
        and hop count; on a replica, its own status (hop, per-hop lag)
        plus any downstream nodes tailing it (fan-out trees)."""
        return self._request("GET", "/apis/kueue/v1beta1/replicas")

    def slo(self) -> dict:
        """Admission-SLO standings (the `kueuectl slo` payload):
        per-ClusterQueue p95 target, attainment ratio and error-budget
        burn rate over the configured window."""
        return self._request("GET", "/apis/kueue/v1beta1/slo")

    # ---- federation ----
    def federation_clusters(self) -> dict:
        """Worker-cluster roster of a federation manager (the
        `kueuectl clusters list` payload): {"items": [...]}.
        404 (ClientError) when the server runs no dispatcher."""
        return self._request("GET", "/apis/federation/v1beta1/clusters")

    def federation_status(self) -> dict:
        """Full federation status: health, clusters, per-workload
        dispatch state (winner + fence), pending retractions."""
        return self._request("GET", "/apis/federation/v1beta1/status")

    def federation_add_worker(
        self, name: str, url: str, token: Optional[str] = None
    ) -> dict:
        """Runtime scale-up: join a worker cluster to the dispatch
        roster (POST /apis/federation/v1beta1/clusters)."""
        body = {"name": name, "url": url}
        if token:
            body["token"] = token
        return self._request(
            "POST", "/apis/federation/v1beta1/clusters", body
        )

    def federation_cordon(self, name: str) -> dict:
        """Stop new dispatches to a worker (existing placements stay)."""
        return self._request(
            "POST", f"/apis/federation/v1beta1/clusters/{name}/cordon"
        )

    def federation_uncordon(self, name: str) -> dict:
        """Readmit a cordoned worker to dispatch."""
        return self._request(
            "POST", f"/apis/federation/v1beta1/clusters/{name}/uncordon"
        )

    def federation_drain(self, name: str) -> dict:
        """Cordon + move every placement off the worker under the
        fencing protocol: {"drained", "deposed"}."""
        return self._request(
            "POST", f"/apis/federation/v1beta1/clusters/{name}/drain"
        )

    def federation_remove_worker(self, name: str) -> dict:
        """Scale-down leave: drain, flush retractions, drop the worker
        (DELETE /apis/federation/v1beta1/clusters/NAME)."""
        return self._request(
            "DELETE", f"/apis/federation/v1beta1/clusters/{name}"
        )

    def capacity(self) -> dict:
        """Elastic capacity plane status (GET /apis/elastic/v1beta1/
        capacity): provider grants, applied requests, in-flight asks,
        last chooser verdict. 404 (ClientError) when --elastic is off."""
        return self._request("GET", "/apis/elastic/v1beta1/capacity")

    def global_standings(self) -> dict:
        """Federation-wide standings (the `kueuectl pending-workloads
        --global` payload): per-worker pending counts, fair-share
        standings and flavor capacities, plus every pending workload's
        per-cluster forecast, current placement and best placement.
        404 (ClientError) when no global scheduler runs."""
        return self._request("GET", "/global/standings")

    # ---- control ----
    def quarantine_list(self) -> dict:
        """Sidelined poison workloads + the solver guard's health
        (GET /debug/quarantine)."""
        return self._request("GET", "/debug/quarantine")

    def quarantine_clear(self, workload: Optional[str] = None) -> dict:
        """Release one quarantined workload ("ns/name") — or all of
        them — back to nomination (POST /debug/quarantine/clear)."""
        body = {"workload": workload} if workload else {}
        return self._request("POST", "/debug/quarantine/clear", body)

    def reconcile(self) -> dict:
        return self._request("POST", "/reconcile")

    def state(self) -> dict:
        return self._request("GET", "/state")

    def solve(self, state: dict, use_solver: bool = True, until_idle: bool = False) -> dict:
        return self._request(
            "POST",
            "/apis/solver/v1beta1/assign",
            {"state": state, "options": {"useSolver": use_solver, "untilIdle": until_idle}},
        )

    def dashboard(self) -> dict:
        return self._request("GET", "/api/dashboard")
