"""kueueviz-equivalent live dashboard.

Reference: cmd/kueueviz — a Go/gin backend streaming cluster state to
a React frontend over websockets. Here the same live views (cluster
queues with quota/usage bars and pending/admitted/evicted counters,
local queues, workloads with admission state, flavors, cohorts, the
event stream, last-cycle phase timings) are computed server-side into
one JSON payload (``dashboard_payload``) and rendered by a single
self-contained HTML page — no external assets, so it works in
air-gapped deployments.

The page is LIVE, not poll-only: it subscribes to the server's
Server-Sent-Events tail (``/events/stream``), appends events as they
arrive, and refetches the payload when the stream reports change
(debounced), falling back to 5 s polling only while the stream is
down. Idle clusters cost one open socket and a heartbeat, not a
request every 2 s.
"""

from __future__ import annotations

from typing import Dict, List

from kueue_tpu.models.constants import WorkloadConditionType


def _workload_state(wl) -> str:
    if wl.is_finished:
        return "Finished"
    if wl.is_admitted:
        return "Admitted"
    if wl.has_quota_reservation:
        return "QuotaReserved"
    ev = wl.conditions.get(WorkloadConditionType.EVICTED)
    if ev is not None and ev.status:
        return "Evicted"
    return "Pending"


def dashboard_payload(rt) -> dict:
    """One read of the runtime -> everything the dashboard shows."""
    cache = rt.cache
    queues = rt.queues

    # per-CQ eviction totals from the scrape surface (summed over
    # reasons) — the counter survives workload deletion, so the tile
    # shows history, not just currently-evicted objects
    evicted_by_cq: Dict[str, float] = {}
    for labels, value in rt.metrics.evicted_workloads_total.series():
        cq = labels.get("cluster_queue", "")
        evicted_by_cq[cq] = evicted_by_cq.get(cq, 0) + value

    cqs: List[dict] = []
    for name, cached in sorted(cache.cluster_queues.items()):
        model = cached.model
        pending = queues.cluster_queues.get(name)
        quota_rows: List[dict] = []
        for rg in model.resource_groups:
            for fq in rg.flavors:
                for rname, rq in fq.resources.items():
                    used = 0
                    for fr, qty in cached.usage.items():
                        if fr.flavor == fq.name and fr.resource == rname:
                            used = qty
                            break
                    quota_rows.append(
                        {
                            "flavor": fq.name,
                            "resource": rname,
                            "used": used,
                            "nominal": rq.nominal,
                            "borrowingLimit": rq.borrowing_limit,
                            "lendingLimit": rq.lending_limit,
                        }
                    )
        cqs.append(
            {
                "name": name,
                "cohort": model.cohort,
                "strategy": model.queueing_strategy.value,
                "stopPolicy": model.stop_policy.value,
                "pendingActive": pending.pending_active() if pending else 0,
                "pendingInadmissible": (
                    pending.pending_inadmissible() if pending else 0
                ),
                "reserving": len(cached.workloads),
                "admitted": sum(
                    1 for w in cached.workloads.values() if w.is_admitted
                ),
                "evicted": int(evicted_by_cq.get(name, 0)),
                "quota": quota_rows,
            }
        )

    lqs = [
        {
            "namespace": lq.namespace,
            "name": lq.name,
            "clusterQueue": lq.cluster_queue,
            "stopPolicy": lq.stop_policy.value,
        }
        for lq in sorted(
            cache.local_queues.values(), key=lambda l: (l.namespace, l.name)
        )
    ]

    audit = getattr(rt, "audit", None)
    workloads: List[dict] = []
    why_pending: List[dict] = []
    reason_tally: Dict[str, int] = {}
    for key, wl in sorted(rt.workloads.items()):
        state = _workload_state(wl)
        workloads.append(
            {
                "key": key,
                "queue": wl.queue_name,
                "priority": wl.priority,
                "state": state,
                "clusterQueue": wl.admission.cluster_queue if wl.admission else "",
            }
        )
        # the "why pending" panel: latest structured reason per
        # not-yet-reserved workload, straight from the audit trail
        if state in ("Pending", "Evicted") and audit is not None:
            latest = audit.latest(key)
            if latest is not None:
                why_pending.append(
                    {
                        "workload": key,
                        "clusterQueue": latest.cluster_queue,
                        "reason": latest.reason.value,
                        "message": latest.message,
                        "count": latest.count,
                        "lastCycle": latest.last_cycle,
                    }
                )
                reason_tally[latest.reason.value] = (
                    reason_tally.get(latest.reason.value, 0) + 1
                )

    state_counts: Dict[str, int] = {}
    for w in workloads:
        state_counts[w["state"]] = state_counts.get(w["state"], 0) + 1

    traces = list(rt.scheduler.last_traces)
    # solver-path badge (core/guard.py): which engine decides the next
    # cycle, breaker state, and the quarantine roster
    guard = getattr(rt.scheduler, "guard", None)
    solver = guard.health() if guard is not None else {}
    quarantine = getattr(rt, "quarantine", None)
    solver["quarantined"] = (
        [e.to_dict() for e in quarantine.items()]
        if quarantine is not None
        else []
    )
    # pipeline badge (core/pipeline.py): drain double-buffering mode +
    # live overlap/discard accounting, next to the solver badge
    pipe_stats = getattr(rt, "pipeline", None)
    pipeline = pipe_stats.to_dict() if pipe_stats is not None else {}
    pipeline["mode"] = getattr(rt, "drain_pipeline", "off")
    # megaloop badge (ops/megaloop_kernel): fused-drain mode + the
    # rounds-per-launch amortization and truncation accounting
    ml_stats = getattr(rt, "megaloop", None)
    megaloop = ml_stats.to_dict() if ml_stats is not None else {}
    megaloop["mode"] = getattr(rt, "drain_megaloop", "off")
    megaloop["pinnedK"] = getattr(rt, "megaloop_rounds", 0)
    # mesh badge (kueue_tpu/parallel): multi-chip admission posture —
    # active mesh shape, device count, jit-bucket reuse
    mesh_status = getattr(rt, "mesh_status", None)
    mesh = mesh_status() if mesh_status is not None else {"shape": "off", "devices": 0}
    # policy badge (kueue_tpu/policy): the active admission policy —
    # green when the default first-fit is in effect, amber for a
    # scoring policy (operators should have what-if'd it first)
    pol = getattr(rt, "policy", None)
    policy = {
        "name": pol.name if pol is not None else "first-fit",
        "default": bool(pol.is_default) if pol is not None else True,
    }
    # replication badge (kueue_tpu/replica): role + staleness —
    # materialized at zero on the leader so the badge renders one
    # schema on every plane
    from kueue_tpu.replica import replication_section

    replication = replication_section(rt)
    # gateway badge (kueue_tpu/gateway): write-path batching posture —
    # queue depth, flush stats, shed counts; {"enabled": False} renders
    # the "direct" badge on planes without a gateway
    gw = getattr(rt, "gateway", None)
    gateway = gw.status() if gw is not None else {"enabled": False}
    # SLO badge + panel (kueue_tpu/gateway/slo.py): per-CQ attainment
    # and error-budget burn against the configured p95 targets
    slo_tracker = getattr(rt, "slo", None)
    if slo_tracker is not None:
        slo_tracker.maybe_refresh()
        slo = slo_tracker.report()
    else:
        slo = {"enabled": False, "degraded": False, "clusterQueues": []}
    # federation health badge (kueue_tpu/federation/health): gray-
    # failure posture — worker probation roster + hedge rate;
    # {"enabled": False} renders the "off" badge on non-manager planes
    fed = getattr(rt, "federation", None)
    if fed is not None and getattr(fed, "worker_health", None) is not None:
        federation = {
            "enabled": True,
            "workers": len(fed.clusters),
            "probation": fed.worker_health.probation(),
            "lost": sorted(
                n for n in fed.clusters
                if not fed.clusters[n].client.active
            ),
            "hedgeRate": round(fed.worker_health.hedge_rate(), 4),
        }
    else:
        federation = {"enabled": False}
    # trace waterfall (kueue_tpu/tracing): the most recent cycle's
    # span tree — on a replica these are the LEADER's spans, mirrored
    # off the journal feed
    tracer = getattr(rt, "tracer", None)
    last_trace = None
    if tracer is not None:
        tid = traces[-1].trace_id if traces else None
        if not tid:
            # replicas never run cycles: fall back to the newest cycle
            # trace in the (ingested) store
            for summary in tracer.traces_summary(limit=32):
                if summary.get("root") == "cycle":
                    tid = summary["traceId"]
                    break
        if tid:
            spans = [s.to_dict() for s in tracer.trace(tid)]
            if spans:
                last_trace = {"traceId": tid, "spans": spans}
    return {
        "lastTrace": last_trace,
        "solver": solver,
        "pipeline": pipeline,
        "megaloop": megaloop,
        "mesh": mesh,
        "policy": policy,
        "replication": replication,
        "gateway": gateway,
        "slo": slo,
        "federation": federation,
        "clusterQueues": cqs,
        "localQueues": lqs,
        "workloads": workloads,
        "workloadStates": state_counts,
        "resourceFlavors": sorted(cache.flavors),
        "cohorts": sorted(cache.cohorts),
        "whyPending": why_pending,
        "pendingReasons": reason_tally,
        # the watch head: a client that refetches can resume its event
        # stream from here without a gap
        "resourceVersion": rt.events.resource_version,
        "lastCycle": traces[-1].to_dict() if traces else None,
        "events": [
            {
                "kind": e.kind,
                "object": e.object_key,
                "message": e.message,
                "count": e.count,
                "resourceVersion": e.resource_version,
            }
            for e in rt.events[-100:]
        ],
    }


DASHBOARD_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>kueue-tpu dashboard</title>
<style>
  :root { --bg:#fafaf8; --fg:#1a1a18; --muted:#6b6b66; --line:#e3e3de;
          --accent:#3b6ea5; --ok:#2e7d4f; --warn:#b3681f; --bad:#a8403a;
          --card:#ffffff; }
  @media (prefers-color-scheme: dark) {
    :root { --bg:#161614; --fg:#ebebe6; --muted:#9a9a92; --line:#33332e;
            --accent:#7aa7d4; --ok:#63b384; --warn:#d79a55; --bad:#d4766f;
            --card:#1f1f1c; }
  }
  body { margin:0; font:14px/1.5 system-ui,sans-serif; background:var(--bg);
         color:var(--fg); padding:24px; }
  h1 { font-size:18px; margin:0 0 4px; } h2 { font-size:14px; margin:24px 0 8px; }
  .muted { color:var(--muted); }
  #mode { font-weight:600; }
  #mode.live { color:var(--ok); } #mode.poll { color:var(--warn); }
  .tiles { display:flex; gap:12px; flex-wrap:wrap; margin:16px 0; }
  .tile { background:var(--card); border:1px solid var(--line); border-radius:8px;
          padding:12px 16px; min-width:110px; }
  .tile b { display:block; font-size:22px; font-weight:600; }
  table { border-collapse:collapse; width:100%; background:var(--card);
          border:1px solid var(--line); border-radius:8px; overflow:hidden; }
  th,td { text-align:left; padding:6px 10px; border-top:1px solid var(--line);
          font-variant-numeric:tabular-nums; }
  th { background:transparent; color:var(--muted); font-weight:500;
       border-top:none; font-size:12px; }
  .bar { background:var(--line); border-radius:3px; height:8px; width:140px;
         display:inline-block; vertical-align:middle; }
  .bar i { display:block; height:8px; border-radius:3px; background:var(--accent); }
  .bar i.over { background:var(--warn); }
  .state-Admitted { color:var(--ok); } .state-Pending { color:var(--muted); }
  .state-Evicted { color:var(--bad); } .state-QuotaReserved { color:var(--warn); }
  .state-Finished { color:var(--muted); }
  .ev-Admitted { color:var(--ok); } .ev-Preempted,.ev-Evicted { color:var(--bad); }
  code { font-size:12px; }
  .badge { display:inline-block; border-radius:10px; padding:1px 10px;
           font-size:12px; font-weight:600; border:1px solid var(--line); }
  .badge.device { color:var(--ok); } .badge.host { color:var(--warn); }
  .badge.quarantined { color:var(--bad); }
</style>
</head>
<body>
<h1>kueue-tpu</h1>
<div class="muted">control-plane dashboard &middot; <span id="mode" class="poll">connecting&hellip;</span>
 &middot; solver <span id="solver" class="badge">&hellip;</span>
 &middot; pipeline <span id="pipeline" class="badge">&hellip;</span>
 &middot; megaloop <span id="megaloop" class="badge">&hellip;</span>
 &middot; mesh <span id="mesh" class="badge">&hellip;</span>
 &middot; policy <span id="policy" class="badge">&hellip;</span>
 &middot; replication <span id="replication" class="badge">&hellip;</span>
 &middot; gateway <span id="gateway" class="badge">&hellip;</span>
 &middot; slo <span id="slo" class="badge">&hellip;</span>
 &middot; federation <span id="federation" class="badge">&hellip;</span></div>
<div class="tiles" id="tiles"></div>
<h2>Last cycle</h2><div id="cycle"></div>
<h2>Trace waterfall</h2><div id="waterfall" class="muted">no trace yet</div>
<h2>ClusterQueues</h2><div id="cqs"></div>
<h2>Why pending</h2><div id="why"></div>
<h2>What would it take?</h2><div id="plan" class="muted">pick <b>plan</b> on a pending workload above to sweep candidate fixes (quota bumps, borrowing lifts) through the capacity planner</div>
<h2>Workloads</h2><div id="wls"></div>
<h2>LocalQueues</h2><div id="lqs"></div>
<h2>Event stream</h2><div id="events"></div>
<script>
function esc(s){return String(s).replace(/[&<>"]/g,c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]))}
function bar(used,nominal){
  const pct = nominal>0 ? Math.min(100*used/nominal,100) : 0;
  const over = nominal>0 && used>nominal;
  return `<span class="bar"><i class="${over?'over':''}" style="width:${pct}%"></i></span>`;
}
const evlog = [];            // live event ring (newest first, capped)
function pushEvent(e){
  evlog.unshift(e);
  if (evlog.length > 100) evlog.pop();
  renderEvents();
}
function renderEvents(){
  document.getElementById('events').innerHTML = '<table><tr><th>rv</th><th>reason</th>'+
    '<th>object</th><th>count</th><th>message</th></tr>'+
    evlog.map(e=>`<tr><td>${e.resourceVersion}</td>`+
      `<td class="ev-${esc(e.reason||e.kind)}">${esc(e.reason||e.kind)}</td>`+
      `<td>${esc(e.object)}</td><td>${e.count||1}</td>`+
      `<td>${esc(e.message)}</td></tr>`).join('')+'</table>';
}
function renderWaterfall(t){
  const el = document.getElementById('waterfall');
  if (!t || !(t.spans||[]).length){ el.innerHTML = '<span class="muted">no trace yet</span>'; return; }
  const spans = t.spans.slice().sort((a,b)=>(a.start-b.start));
  const t0 = Math.min(...spans.map(s=>s.start));
  const t1 = Math.max(...spans.map(s=>s.start + (s.durationMs||0)/1e3));
  const total = Math.max(t1 - t0, 1e-9);
  el.innerHTML = `<div class="muted" style="margin-bottom:4px">trace <code>${esc(t.traceId)}</code>`+
    ` &middot; ${spans.length} spans &middot; ${(total*1e3).toFixed(2)} ms</div>`+
    '<table>'+spans.map(s=>{
      const left = 100*(s.start - t0)/total;
      const w = Math.max(100*((s.durationMs||0)/1e3)/total, 0.5);
      const dur = s.durationMs==null ? 'open' : s.durationMs.toFixed(3)+' ms';
      const depth = s.parentId ? 1 : 0;
      return `<tr><td style="padding-left:${10+depth*14}px;white-space:nowrap"><code>${esc(s.name)}</code></td>`+
        `<td style="width:55%"><span class="bar" style="width:100%"><i style="margin-left:${left}%;width:${w}%"></i></span></td>`+
        `<td class="muted" style="white-space:nowrap">${dur}</td></tr>`;
    }).join('')+'</table>';
}
function render(d){
  renderWaterfall(d.lastTrace);
  const sv = d.solver||{};
  const svEl = document.getElementById('solver');
  if (sv.path){
    const q = (sv.quarantined||[]).length;
    const cls = sv.breaker==='quarantined' ? 'quarantined' : sv.path;
    svEl.className = 'badge '+cls;
    svEl.textContent = sv.path + (sv.breaker!=='closed' ? ` (${sv.breaker})` : '')
      + (q ? ` · ${q} quarantined wl` : '');
    svEl.title = `mode=${sv.mode} failovers=${sv.failovers} `+
      `divergences=${sv.divergences}/${sv.divergenceChecks} checks `+
      `containedCycles=${sv.containedCycles}`;
  }
  const pl = d.pipeline||{};
  const plEl = document.getElementById('pipeline');
  if (pl.mode){
    plEl.className = 'badge '+(pl.mode==='on' ? 'device' : 'host');
    plEl.textContent = pl.mode + (pl.rounds ?
      ` · ${Math.round((pl.overlapRatio||0)*100)}% overlap` : '');
    plEl.title = `rounds=${pl.rounds||0} prefetches=${pl.prefetches||0} `+
      `commits=${pl.commits||0} discards=${pl.discards||0} `+
      `inflight=${pl.inflight||0}`;
  }
  const ml = d.megaloop||{};
  const mlEl = document.getElementById('megaloop');
  if (mlEl){
    mlEl.className = 'badge '+(ml.mode==='on' ? 'device' : 'host');
    mlEl.textContent = (ml.mode==='on')
      ? ('on'+(ml.launches ? ` · ${ml.roundsPerLaunch||0} rounds/launch` : ''))
      : 'off';
    mlEl.title = `launches=${ml.launches||0} rounds=${ml.rounds||0} `+
      `truncations=${ml.truncations||0} exhausted=${ml.exhausted||0} `+
      `K=${ml.pinnedK||'auto'}`;
  }
  const ms = d.mesh||{};
  const msEl = document.getElementById('mesh');
  msEl.className = 'badge '+(ms.devices>1 ? 'device' : 'host');
  msEl.textContent = ms.devices>1 ? `${ms.shape} · ${ms.devices} devices` : 'off';
  const bk = (ms.buckets||{});
  msEl.title = `jit buckets: ${bk.buckets||0} compiled, ${bk.hits||0} reuses; `+
    `place=${ms.placeSeconds||0}s`;
  const po = d.policy||{};
  const poEl = document.getElementById('policy');
  poEl.className = 'badge '+(po.default===false ? 'host' : 'device');
  poEl.textContent = po.name || 'first-fit';
  poEl.title = po.default===false
    ? 'scoring admission policy active (kueue_policy_* metrics)'
    : 'default first-fit policy (bit-for-bit reference decisions)';
  const rp = d.replication||{};
  const rpEl = document.getElementById('replication');
  if (rp.role){
    const lag = rp.lagSeconds||0;
    rpEl.className = 'badge '+(rp.role==='replica'
      ? (rp.lastError ? 'quarantined' : (lag > 2 ? 'host' : 'device'))
      : 'host');
    rpEl.textContent = rp.role==='replica'
      ? `replica · seq ${rp.appliedSeq||0} · lag ${lag.toFixed ? lag.toFixed(2) : lag}s`
      : rp.role;
    rpEl.title = `appliedSeq=${rp.appliedSeq||0} lag=${lag}s `+
      `recordsApplied=${rp.recordsApplied||0} resyncs=${rp.resyncs||0}`+
      (rp.lastError ? ` lastError=${rp.lastError}` : '');
  }
  const gw = d.gateway||{};
  const gwEl = document.getElementById('gateway');
  if (gw.enabled){
    const shed = Object.values(gw.shed||{}).reduce((a,b)=>a+b,0);
    gwEl.className = 'badge '+(shed>0 ? 'host' : 'device');
    gwEl.textContent = `batching · q${gw.queueDepth||0} · shed ${shed}`;
    gwEl.title = `flush=${(gw.flushIntervalS*1e3).toFixed(1)}ms `+
      `batches=${gw.batches||0} applied=${gw.applied||0} `+
      `lastBatch=${gw.lastBatch||0} shed=${JSON.stringify(gw.shed||{})}`;
  } else { gwEl.className='badge'; gwEl.textContent='direct'; }
  const so = d.slo||{};
  const soEl = document.getElementById('slo');
  if (so.enabled){
    const worst = (so.clusterQueues||[]).reduce(
      (w,e)=>Math.max(w, e.burnRate||0), 0);
    soEl.className = 'badge '+(so.degraded ? 'quarantined'
      : (worst > (so.burnThreshold||2) ? 'host' : 'device'));
    soEl.textContent = so.degraded ? 'BURNING'
      : `ok · worst burn ${worst.toFixed(2)}x`;
    soEl.title = (so.clusterQueues||[]).map(
      e=>`${e.clusterQueue}: target=${e.targetSeconds}s `+
         `attainment=${((e.attainment||1)*100).toFixed(2)}% `+
         `burn=${(e.burnRate||0).toFixed(2)}x`).join('\\n')
      || 'no admissions observed yet';
  } else { soEl.className='badge'; soEl.textContent='off'; }
  const fd = d.federation||{};
  const fdEl = document.getElementById('federation');
  if (fd.enabled){
    const gray = (fd.probation||[]).length, lost = (fd.lost||[]).length;
    fdEl.className = 'badge '+(lost>0 ? 'quarantined'
      : (gray>0 ? 'host' : 'device'));
    fdEl.textContent = lost>0 ? `${lost} lost · ${gray} gray`
      : (gray>0 ? `${gray} gray / ${fd.workers}` : `${fd.workers} healthy`);
    fdEl.title = `probation=${(fd.probation||[]).join(',')||'-'} `+
      `lost=${(fd.lost||[]).join(',')||'-'} hedgeRate=${fd.hedgeRate||0}`;
  } else { fdEl.className='badge'; fdEl.textContent='off'; }
  const st = d.workloadStates||{};
  document.getElementById('tiles').innerHTML =
    [['ClusterQueues',d.clusterQueues.length],['LocalQueues',d.localQueues.length],
     ['Workloads',d.workloads.length],['Admitted',st.Admitted||0],
     ['Pending',st.Pending||0],['Evicted',st.Evicted||0],
     ['Flavors',d.resourceFlavors.length],['Cohorts',d.cohorts.length]]
    .map(([k,v])=>`<div class="tile"><b>${v}</b><span class="muted">${k}</span></div>`).join('');
  const c = d.lastCycle;
  document.getElementById('cycle').innerHTML = !c ? '<span class="muted">no cycles yet</span>' :
    '<table><tr><th>cycle</th><th>resolution</th><th>heads</th><th>admitted</th>'+
    '<th>preempting</th><th>total</th><th>device</th><th>host</th><th>phases</th></tr>'+
    `<tr><td>${c.cycle}</td><td>${esc(c.resolution)}</td><td>${c.heads}</td>`+
    `<td>${c.admitted}</td><td>${c.preempting}</td><td>${c.totalMs} ms</td>`+
    `<td>${c.deviceMs} ms</td><td>${c.hostMs} ms</td><td><code>`+
    Object.entries(c.spansMs||{}).map(([k,v])=>`${esc(k)}=${v}`).join(' ')+
    `</code></td></tr></table>`;
  document.getElementById('cqs').innerHTML = '<table><tr><th>name</th><th>cohort</th>'+
    '<th>pending</th><th>admitted</th><th>evicted</th><th>quota (used / nominal)</th></tr>'+
    d.clusterQueues.map(cq=>`<tr><td>${esc(cq.name)}</td><td>${esc(cq.cohort||'')}</td>`+
      `<td>${cq.pendingActive}+${cq.pendingInadmissible}</td><td>${cq.admitted}</td>`+
      `<td>${cq.evicted||0}</td><td>`+
      cq.quota.map(q=>`${esc(q.flavor)}/${esc(q.resource)} ${bar(q.used,q.nominal)} `+
        `<code>${q.used}/${q.nominal}</code>`).join('<br>')+
      `</td></tr>`).join('')+'</table>';
  const why = d.whyPending||[];
  const tally = Object.entries(d.pendingReasons||{}).sort((a,b)=>b[1]-a[1])
    .map(([r,n])=>`<span class="tile" style="padding:4px 10px;min-width:0">`+
      `<b style="font-size:14px;display:inline">${n}</b> <span class="muted">${esc(r)}</span></span>`).join(' ');
  document.getElementById('why').innerHTML = !why.length
    ? '<span class="muted">nothing pending with a recorded decision</span>'
    : `<div class="tiles">${tally}</div>`+
      '<table><tr><th>workload</th><th>clusterQueue</th><th>reason</th>'+
      '<th>seen</th><th>last cycle</th><th>message</th><th></th></tr>'+
      why.slice(0,200).map(p=>`<tr><td>${esc(p.workload)}</td>`+
        `<td>${esc(p.clusterQueue)}</td><td class="ev-Evicted">${esc(p.reason)}</td>`+
        `<td>&times;${p.count}</td><td>${p.lastCycle}</td>`+
        `<td>${esc(p.message)}</td>`+
        `<td><a href="#plan" onclick="plan('${esc(p.workload)}');return true">plan</a></td></tr>`).join('')+'</table>';
  document.getElementById('wls').innerHTML = '<table><tr><th>workload</th><th>queue</th>'+
    '<th>priority</th><th>state</th><th>clusterQueue</th></tr>'+
    d.workloads.slice(0,500).map(w=>`<tr><td>${esc(w.key)}</td><td>${esc(w.queue)}</td>`+
      `<td>${w.priority}</td><td class="state-${w.state}">${w.state}</td>`+
      `<td>${esc(w.clusterQueue)}</td></tr>`).join('')+'</table>';
  document.getElementById('lqs').innerHTML = '<table><tr><th>namespace</th><th>name</th>'+
    '<th>clusterQueue</th><th>stopPolicy</th></tr>'+
    d.localQueues.map(l=>`<tr><td>${esc(l.namespace)}</td><td>${esc(l.name)}</td>`+
      `<td>${esc(l.clusterQueue)}</td><td>${l.stopPolicy}</td></tr>`).join('')+'</table>';
  if (!evlog.length && d.events) {           // seed the log once from the payload
    d.events.slice().reverse().forEach(e=>{ evlog.unshift(e); if(evlog.length>100) evlog.pop(); });
    renderEvents();
  }
}
async function refetch(){
  try { render(await (await fetch('/api/dashboard')).json()); } catch(e) {}
}
async function plan(key){            // the "What would it take?" panel
  const el = document.getElementById('plan');
  el.innerHTML = `<span class="muted">planning for ${esc(key)}&hellip;</span>`;
  try {
    const r = await fetch('/debug/plan', {method:'POST',
      headers:{'Content-Type':'application/json'},
      body: JSON.stringify({target:{workload:key},
                            options:{includeReasons:'baseline'}})});
    if (!r.ok) throw new Error((await r.json()).error || r.status);
    const d = await r.json();
    const rec = d.recommended
      ? `<p>Recommended: <b>${esc(d.recommended)}</b></p>`
      : '<p class="muted">no evaluated scenario admits anything the baseline does not</p>';
    el.innerHTML = `<p class="muted">target ${esc(key)} &middot; `+
      `${d.heads} heads &middot; ${d.backend} &middot; ${d.durationMs} ms</p>`+rec+
      '<table><tr><th>scenario</th><th>admits</th><th>new</th>'+
      '<th>preempt</th><th>borrow</th><th>deltas</th></tr>'+
      d.scenarios.map(s=>`<tr><td>${esc(s.name)}${s.baseline?' *':''}</td>`+
        `<td>${s.admitted.length}</td><td>+${s.newlyAdmitted.length}</td>`+
        `<td>${s.preemptionCandidates}</td><td>${s.borrowing}</td>`+
        `<td><code>${s.deltas.map(esc).join('; ')}</code></td></tr>`).join('')+
      '</table>';
  } catch(e) { el.innerHTML = `<span class="ev-Evicted">plan failed: ${esc(e.message||e)}</span>`; }
}
let refetchTimer = null;
function scheduleRefetch(){          // debounce: one fetch per burst of events
  if (refetchTimer) return;
  refetchTimer = setTimeout(()=>{ refetchTimer = null; refetch(); }, 250);
}
let pollTimer = null;
function setMode(live){
  const el = document.getElementById('mode');
  el.textContent = live ? 'live (event stream)' : 'polling /api/dashboard every 5s';
  el.className = live ? 'live' : 'poll';
  if (live && pollTimer) { clearInterval(pollTimer); pollTimer = null; }
  if (!live && !pollTimer) pollTimer = setInterval(refetch, 5000);
}
function connect(){
  const es = new EventSource('/events/stream');
  es.onopen = ()=>setMode(true);
  es.onmessage = (m)=>{ try { pushEvent(JSON.parse(m.data)); } catch(e) {} scheduleRefetch(); };
  es.addEventListener('reset', ()=>{ evlog.length = 0; refetch(); });
  es.onerror = ()=>setMode(false);   // EventSource auto-reconnects with Last-Event-ID
}
refetch(); connect();
</script>
</body>
</html>
"""
