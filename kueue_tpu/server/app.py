"""HTTP/JSON server: object API, visibility, metrics, jax-assign.

Routes (all JSON unless noted):

  GET  /healthz | /readyz                      liveness/readiness probes
                                               (cmd/kueue/main.go:181-189)
  GET  /metrics                                Prometheus text exposition
                                               (cmd/kueue/main.go:154-179)
  GET  /apis/visibility/v1beta1/clusterqueues/{cq}/pendingworkloads
  GET  /apis/visibility/v1beta1/namespaces/{ns}/localqueues/{lq}/pendingworkloads
                                               (pkg/visibility/server.go:62-118,
                                               api/v1beta1/pending_workloads_cq.go:37-46;
                                               items carry the latest structured
                                               inadmissibleReason from the audit trail)
  GET  /debug/workloads/{ns}/{name}/decisions  per-workload decision audit
                                               trail (core/audit.py) — the
                                               `kueuectl explain` payload
  POST /debug/plan                             what-if capacity planner
                                               (kueue_tpu/planner): scenario
                                               deltas (or an auto-generated
                                               sweep for a target) solved in
                                               one vmapped device launch —
                                               strictly read-only, leader
                                               only (forecasts the LEADER's
                                               next decisions; standby state
                                               may lag)
  GET  /apis/kueue/v1beta1/{section}           list objects w/ status
  POST /apis/kueue/v1beta1/{section}           upsert one object (webhook
                                               defaulting+validation applied)
  DELETE /apis/kueue/v1beta1/workloads/{ns}/{name}
  DELETE /apis/kueue/v1beta1/{clusterqueues|resourceflavors|nodes}/{name}
  POST /apis/kueue/v1beta1/workloads/{ns}/{name}/admissionchecks
                                               flip a check state — the
                                               phase-2 plugin boundary
                                               (admissioncheck_types.go:23-45)
  GET  /apis/kueue/v1beta1/journal?sinceSeq=N  replication feed (leader):
                                               journal records past N bundled
                                               with event-recorder and audit
                                               deltas — the read-replica tail
                                               (storage/tailer.py); registers
                                               the polling replica in the
                                               roster
  GET  /apis/kueue/v1beta1/replicas            replica roster (leader) or
                                               this replica's own status
  GET  /apis/kueue/v1beta1/events              recorded events (+resourceVersion)
  GET  /apis/kueue/v1beta1/{section}?watch=1&resourceVersion=N
                                               long-poll: blocks until events
                                               newer than N land (410 when N
                                               fell out of the ring — relist)
  GET  /events/stream                          Server-Sent-Events live tail
                                               (id: = resourceVersion, resumes
                                               via Last-Event-ID)
  POST /reconcile                              run_until_idle; returns cycles
  GET  /state                                  full state dump (checkpoint)
  POST /apis/solver/v1beta1/assign             stateless jax-assign: body is
                                               a serialized snapshot, reply
                                               is per-workload decisions
  GET  /                                       dashboard (kueueviz analog)
  GET  /api/dashboard                          dashboard JSON feed

The server owns one ClusterRuntime guarded by an RLock; handlers are
thin translations between the wire format (serialization.py) and
runtime calls. ThreadingHTTPServer gives per-request threads the way
the reference's apiservers do per-connection goroutines.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from kueue_tpu import serialization as ser
from kueue_tpu import visibility
from kueue_tpu.models.constants import (
    AdmissionCheckStateType,
    WorkloadConditionType,
)


class _ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a serving-tier accept backlog. The
    stdlib default listen(5) RSTs concurrent connections the moment
    more than a handful of writers arrive between accept() calls —
    at gateway-scale ingest (dozens of concurrent POSTs) that
    surfaces as ConnectionResetError on the client."""

    request_queue_size = 256


class ApiError(Exception):
    def __init__(self, status: int, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        # 429 load-shedding: surfaced as the Retry-After header so
        # clients (KueueClient honors it with capped jittered backoff)
        # pace themselves instead of hammering a saturated gateway
        self.retry_after_s = retry_after_s


class _Section:
    """One object kind's wiring: wire<->model codecs, the runtime add
    method, and its store (one accessor serves both the point lookup
    and listing, so the mappings cannot diverge)."""

    def __init__(self, from_dict, to_dict, add_name, store_map, namespaced=False):
        self.from_dict = from_dict
        self.to_dict = to_dict
        self.add_name = add_name
        self.store_map = store_map  # runtime -> {key: model}
        self.namespaced = namespaced

    def lookup(self, rt, namespace: str, name: str):
        key = f"{namespace}/{name}" if self.namespaced else name
        return self.store_map(rt).get(key)


_SECTIONS: Dict[str, _Section] = {
    "resourceflavors": _Section(
        ser.flavor_from_dict, ser.flavor_to_dict, "add_flavor",
        lambda rt: rt.cache.flavors,
    ),
    "clusterqueues": _Section(
        ser.cq_from_dict,
        lambda m: ser.cq_to_dict(m.model if hasattr(m, "model") else m),
        "add_cluster_queue",
        lambda rt: rt.cache.cluster_queues,
    ),
    "localqueues": _Section(
        ser.lq_from_dict, ser.lq_to_dict, "add_local_queue",
        lambda rt: rt.cache.local_queues, namespaced=True,
    ),
    "workloads": _Section(
        ser.workload_from_dict, ser.workload_to_dict, "add_workload",
        lambda rt: rt.workloads, namespaced=True,
    ),
    "cohorts": _Section(
        ser.cohort_from_dict, ser.cohort_to_dict, "add_cohort",
        lambda rt: rt.cache.cohorts,
    ),
    "admissionchecks": _Section(
        ser.check_from_dict, ser.check_to_dict, "add_admission_check",
        lambda rt: rt.cache.admission_checks,
    ),
    "topologies": _Section(
        ser.topology_from_dict, ser.topology_to_dict, "add_topology",
        lambda rt: rt.cache.topologies,
    ),
    # TAS node inventory (the corev1.Node watch analog: a standalone
    # control plane ingests its topology capacity through its own API)
    "nodes": _Section(
        ser.node_from_dict, ser.node_to_dict, "add_node",
        lambda rt: (
            rt.cache.tas_cache.node_inventory
            if rt.cache.tas_cache is not None
            else {}
        ),
    ),
    "workloadpriorityclasses": _Section(
        ser.priority_class_from_dict, ser.priority_class_to_dict,
        "add_priority_class",
        lambda rt: rt.cache.priority_classes,
    ),
    # "events" is NOT here: it is read-only and served straight from
    # the runtime's EventRecorder (list + watch below), never upserted

    "limitranges": _Section(
        ser.limit_range_from_dict, ser.limit_range_to_dict, "add_limit_range",
        lambda rt: rt.limit_ranges, namespaced=True,
    ),
    "runtimeclasses": _Section(
        ser.runtime_class_from_dict, ser.runtime_class_to_dict,
        "add_runtime_class",
        lambda rt: rt.runtime_classes,
    ),
}


def solve_assign(request: dict) -> dict:
    """The ``jax-assign`` service: one nomination pass (or a full drain
    to idle) over a serialized snapshot, on the batched TPU solver.

    Stateless by design — the AdmissionCheck contract
    (admissioncheck_types.go:23-45) is that the controller observes a
    workload + cluster state and reports a verdict; feeding it explicit
    snapshots keeps the service free of watch machinery and lets one
    server serve many control planes.
    """
    state = request.get("state")
    if not isinstance(state, dict):
        raise ApiError(400, "body must carry a 'state' object")
    opts = request.get("options", {})
    use_solver = bool(opts.get("useSolver", True))
    until_idle = bool(opts.get("untilIdle", False))
    rt = ser.runtime_from_state(
        state,
        use_solver=use_solver,
        use_preempt_solver=use_solver,
    )
    cycles = 0
    decisions: List[dict] = []
    preemptions: List[dict] = []

    def observe(result) -> None:
        # per-cycle preemption targets via the scheduler's first-class
        # cycle-result hook; the bulk drain path reports through the
        # same surface (ClusterRuntime.bulk_drain -> notify_cycle)
        for entry in result.preempting:
            for tgt in entry.preemption_targets:
                preemptions.append(
                    {
                        "victim": tgt.workload.workload.key,
                        "by": entry.workload.key,
                        "reason": tgt.reason,
                    }
                )

    rt.scheduler.cycle_observers.append(observe)
    try:
        if until_idle:
            cycles = rt.run_until_idle()
        else:
            rt.schedule_once()
            cycles = 1
    finally:
        rt.scheduler.cycle_observers.remove(observe)
    for key in sorted(rt.workloads):
        wl = rt.workloads[key]
        item = {
            "workload": key,
            "outcome": (
                "Admitted"
                if wl.is_admitted
                else "QuotaReserved"
                if wl.has_quota_reservation
                else "Pending"
            ),
        }
        if wl.admission is not None:
            item["admission"] = ser.workload_to_dict(wl)["admission"]
        else:
            latest = rt.audit.latest(key)
            if latest is not None:
                item["inadmissibleReason"] = latest.reason.value
                item["message"] = latest.message
        decisions.append(item)
    return {
        "cycles": cycles,
        "decisions": decisions,
        "preemptions": preemptions,
        "resolution": "device" if use_solver else "host",
    }


class KueueServer:
    """Owns the runtime + HTTP server. start()/stop() for embedding in
    tests; ``python -m kueue_tpu.server`` for standalone use."""

    def __init__(
        self,
        runtime=None,
        host: str = "127.0.0.1",
        port: int = 0,
        auto_reconcile: bool = True,
        validators: Optional[list] = None,
        elector=None,  # utils.lease.LeaderElector: HA replica mode
        auth_token: Optional[str] = None,
        tls=None,  # utils.cert.CertRotator, or (cert_path, key_path)
        replica=None,  # replica.ReadReplica: journal-tailing follower
        gateway=None,  # gateway.WriteGateway: coalescing write path
    ):
        if runtime is None:
            from kueue_tpu.controllers import ClusterRuntime
            from kueue_tpu.tas import TASCache

            # TAS-capable by default: a standalone control plane must
            # be able to ingest node inventory through its own API
            runtime = ClusterRuntime(tas_cache=TASCache())
        self.runtime = runtime
        self.lock = threading.RLock()
        # serving-surface clock: the runtime's injected clock when it
        # has one (FakeClock tests drive roster staleness and feed
        # leaderTime through it), a fresh Clock otherwise
        clock = getattr(runtime, "clock", None)
        if clock is None:
            from kueue_tpu.utils.clock import Clock

            clock = Clock()
        self.clock = clock
        self.auto_reconcile = auto_reconcile
        if validators is None:
            from kueue_tpu.webhooks import default_admission_chain

            validators = default_admission_chain()
        # admission chain: callables (section, obj_dict, old_obj|None,
        # runtime) -> possibly-mutated obj_dict, raising ApiError on
        # rejection (the webhook layer; pkg/webhooks/webhooks.go:25)
        self.validators = list(validators)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._port = port
        # Leader election (leader_aware_reconciler.go analog): with an
        # elector configured, only the leader accepts mutating calls;
        # standbys keep serving reads (visibility, metrics, dashboard,
        # stateless solves) and take over when the lease lapses.
        self.elector = elector
        # Bearer-token authentication for the secured surface: mutating
        # routes, metrics, state and debug (the reference serves metrics
        # behind authn/z and its write paths through the authenticated
        # apiserver — cmd/kueue/main.go:154-179). None = open (dev mode,
        # in-cluster behind a NetworkPolicy). Probes, visibility and the
        # dashboard stay open either way.
        self.auth_token = auth_token
        # TLS serving (cmd/kueue/main.go:154-179: secure serving with a
        # cert watcher over rotated files). A CertRotator gives
        # self-managed certs with pre-expiry rotation hot-reloaded into
        # the live SSLContext; a (cert, key) path pair is the
        # provided-certificates mode.
        self.tls = tls
        self._ssl_context = None
        self._tls_rotation_stop = threading.Event()
        self._tls_rotation_thread: Optional[threading.Thread] = None
        self._election_stop = threading.Event()
        self._election_thread: Optional[threading.Thread] = None
        # checkpoint ordering (used by __main__.fenced_checkpoint): a
        # snapshot serialized earlier must never replace one serialized
        # later, even if its disk write happens last
        self._ckpt_seq = 0
        self._ckpt_written = 0
        self._ckpt_write_lock = threading.Lock()
        # flipped by stop(): parked watch long-polls and SSE tails
        # check it so shutdown never waits out a full poll window
        self._stopping = threading.Event()
        # Read-replica mode (kueue_tpu/replica): a journal-tailing
        # follower serving watch/SSE, visibility, explain and
        # best-effort-stale plan from replayed leader state; every
        # mutating route 307-redirects to the leader. The replica
        # installs its runtime (and every resync rebuild) through
        # self.lock, replacing whatever runtime= was passed.
        self.replica = replica
        # leader-side follower roster, fed by the replication feed's
        # ?replica=...&appliedSeq=... poll params (kueuectl replicas)
        self.replica_roster: Dict[str, dict] = {}
        if replica is not None:
            replica.attach(self)
        # Write-path gateway (kueue_tpu/gateway): when attached, every
        # workload POST / batch section drains through the bounded
        # coalescing queue — one serving-lock critical section, one
        # group-committed journal sync and one admission pass per flush
        # window — with per-tenant token-bucket shedding (429 +
        # Retry-After). Leader-side only (replicas redirect writes).
        self.gateway = gateway
        if gateway is not None:
            gateway.attach(self)

    def require_leader(self) -> None:
        if self.elector is not None and not self.elector.is_leader:
            raise ApiError(
                503,
                "not leader; writes are served by "
                f"{self.elector.lease.holder() or 'no current holder'}",
            )

    # ---- object API ----
    def _find_existing(self, section: str, obj: dict):
        """Wire dict of the stored object with the same identity, via a
        direct store lookup (no full-state serialization on the ingest
        path)."""
        sec = _SECTIONS.get(section)
        if sec is None:
            return None
        model = sec.lookup(
            self.runtime, obj.get("namespace", ""), obj.get("name", "")
        )
        return sec.to_dict(model) if model is not None else None

    def apply(self, section: str, obj: dict, reconcile: bool = True) -> dict:
        """Upsert one object through the webhook admission chain."""
        sec = _SECTIONS.get(section)
        if sec is None:
            raise ApiError(404, f"unknown section {section!r}")
        from kueue_tpu.webhooks import ValidationError

        self.require_leader()
        with self.lock:
            old = self._find_existing(section, obj)
            try:
                for admit in self.validators:
                    obj = admit(section, obj, old, self.runtime)
            except ValidationError as e:
                raise ApiError(422, str(e))
            if section == "nodes" and self.runtime.cache.tas_cache is None:
                # add_node would silently no-op: acknowledging a write
                # we discarded is worse than refusing it
                raise ApiError(
                    409, "runtime has no TAS cache; node inventory disabled"
                )
            try:
                model = sec.from_dict(obj)
            except (KeyError, TypeError, ValueError) as e:
                # a codec miss is the CALLER's malformed body, not a
                # server fault — 400, never a 500 stack trace
                raise ApiError(400, f"malformed {section} object: {e!r}")
            getattr(self.runtime, sec.add_name)(model)
            if reconcile and self.auto_reconcile:
                self.runtime.run_until_idle()
        return obj

    def delete(self, section: str, namespace: str, name: str) -> None:
        self.require_leader()
        with self.lock:
            if section == "workloads":
                wl = self.runtime.workloads.get(f"{namespace}/{name}")
                if wl is None:
                    raise ApiError(404, f"workload {namespace}/{name} not found")
                self.runtime.delete_workload(wl)
            elif section == "clusterqueues":
                if name not in self.runtime.cache.cluster_queues:
                    raise ApiError(404, f"clusterqueue {name} not found")
                self.runtime.delete_cluster_queue(name)
            elif section == "resourceflavors":
                if name not in self.runtime.cache.flavors:
                    raise ApiError(404, f"resourceflavor {name} not found")
                try:
                    self.runtime.delete_flavor(name)
                except ValueError as e:
                    # the ResourceFlavor finalizer's user-visible effect
                    raise ApiError(409, str(e))
            elif section == "nodes":
                tc = self.runtime.cache.tas_cache
                if tc is None or name not in tc.node_inventory:
                    raise ApiError(404, f"node {name} not found")
                self.runtime.delete_node(name)
            else:
                raise ApiError(405, f"delete not supported for {section}")
            if self.auto_reconcile:
                self.runtime.run_until_idle()

    def set_admission_check_state(
        self, namespace: str, name: str, check: str, state: str, message: str = ""
    ) -> None:
        """External controller flips a check — phase 2 of two-phase
        admission (workload_controller.go:251-275 syncs the Admitted
        condition on the next reconcile)."""
        self.require_leader()
        with self.lock:
            wl = self.runtime.workloads.get(f"{namespace}/{name}")
            if wl is None:
                raise ApiError(404, f"workload {namespace}/{name} not found")
            try:
                state_t = AdmissionCheckStateType(state)
            except ValueError:
                raise ApiError(400, f"invalid check state {state!r}")
            from kueue_tpu.models.admission_check import AdmissionCheckState

            wl.admission_check_states[check] = AdmissionCheckState(
                name=check, state=state_t, message=message
            )
            if self.auto_reconcile:
                self.runtime.run_until_idle()

    def get_object(self, section: str, namespace: str, name: str) -> dict:
        sec = _SECTIONS.get(section)
        if sec is None:
            raise ApiError(404, f"unknown section {section!r}")
        with self.lock:
            model = sec.lookup(self.runtime, namespace, name)
            if model is None:
                raise ApiError(404, f"{section[:-1]} {namespace}/{name} not found")
            obj = sec.to_dict(model)
            if section == "clusterqueues":
                # QueueVisibility (gated): the reference publishes the
                # interval snapshots into CQ .status.pendingWorkloadsStatus
                # (clusterqueue_controller.go snapshot worker)
                snap = self.runtime.cq_pending_snapshots.get(name)
                if snap is not None:
                    obj.setdefault("status", {})["pendingWorkloadsStatus"] = {
                        "clusterQueuePendingWorkload": snap,
                    }
            return obj

    @staticmethod
    def validate_batch_body(body: dict) -> None:
        """Shape check shared by the serial and gateway batch paths:
        unknown sections and non-list values are the CALLER's malformed
        request — refused whole, before anything applies."""
        unknown = [s for s in body if s not in _SECTIONS]
        if unknown:
            raise ApiError(404, f"unknown sections {unknown}")
        for section, objs in body.items():
            if not isinstance(objs, list):
                raise ApiError(400, f"section {section!r} must be a list")

    def apply_batch(self, body: dict) -> dict:
        """Bulk upsert: {section: [objects]} in one request (the
        MultiKueue batched-dispatch wire). Each object still passes the
        webhook admission chain; reconcile runs once at the end.

        Partial-failure semantics: one bad object rejects THAT object,
        not the whole batch — the response carries per-section
        applied/rejected counts plus the first error, so a mixed batch
        lands its good workloads while the caller learns exactly what
        bounced (HTTPTransport.create_workloads turns a non-empty
        rejected map back into RemoteRejected for federation)."""
        self.require_leader()
        self.validate_batch_body(body)
        applied: Dict[str, int] = {}
        rejected: Dict[str, int] = {}
        first_error: Optional[str] = None
        any_applied = False
        for section, objs in body.items():
            for i, obj in enumerate(objs):
                try:
                    self.apply(section, obj, reconcile=False)
                    applied[section] = applied.get(section, 0) + 1
                    any_applied = True
                except ApiError as e:
                    rejected[section] = rejected.get(section, 0) + 1
                    if first_error is None:
                        first_error = f"{section}[{i}]: {e.message}"
        if self.auto_reconcile and any_applied:
            with self.lock:
                self.runtime.run_until_idle()
        return {
            "applied": applied,
            "rejected": rejected,
            "firstError": first_error,
        }

    def list_section(self, section: str) -> dict:
        sec = _SECTIONS.get(section)
        if sec is None:
            raise ApiError(404, f"unknown section {section!r}")
        with self.lock:
            items = [
                sec.to_dict(m)
                for _, m in sorted(sec.store_map(self.runtime).items())
            ]
        return {"items": items}

    # ---- http plumbing ----
    def _load_certs(self) -> None:
        """(Re)load the serving cert into the live SSLContext — new
        handshakes pick it up immediately (the certwatcher analog)."""
        if hasattr(self.tls, "cert_path"):
            cert_path, key_path = self.tls.cert_path, self.tls.key_path
        else:
            cert_path, key_path = self.tls
        self._ssl_context.load_cert_chain(cert_path, key_path)

    def _tls_rotation_loop(self, period: float) -> None:
        import sys
        import traceback

        while not self._tls_rotation_stop.wait(period):
            try:
                self.tls.maybe_rotate()
            except Exception:  # noqa: BLE001 — a transient IO error on
                # the cert volume must not kill the rotation loop (the
                # cert would then silently expire in place) — but it
                # must be VISIBLE: a persistently failing rotation ends
                # in an expired cert ~a refresh window later, and the
                # operator needs the trail
                print("tls cert rotation failed:", file=sys.stderr)
                traceback.print_exc()

    def start(self, tls_rotation_period_s: float = 3600.0) -> int:
        self._stopping.clear()
        handler = _make_handler(self)
        self._httpd = _ServingHTTPServer((self._host, self._port), handler)
        if self.tls is not None:
            import ssl

            if hasattr(self.tls, "ensure"):
                self.tls.ensure()
            self._ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._load_certs()
            if hasattr(self.tls, "reload_hooks"):
                self.tls.reload_hooks.append(self._load_certs)
            # handshake lazily in the per-request worker thread, NOT in
            # the accept loop: with the default do_handshake_on_connect
            # a single client that connects and sends nothing would
            # block accept() — and with it every other connection,
            # including the HTTPS probes — indefinitely. The handler
            # timeout below bounds a stalled handshake to its own
            # worker thread.
            self._httpd.socket = self._ssl_context.wrap_socket(
                self._httpd.socket,
                server_side=True,
                do_handshake_on_connect=False,
            )
            if handler.timeout is None:
                handler.timeout = 60.0
            if hasattr(self.tls, "maybe_rotate"):
                self._tls_rotation_stop.clear()
                self._tls_rotation_thread = threading.Thread(
                    target=self._tls_rotation_loop,
                    args=(tls_rotation_period_s,),
                    daemon=True,
                )
                self._tls_rotation_thread.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        if self.gateway is not None:
            self.gateway.start()
        if self.elector is not None:
            self.elector.tick()  # contend immediately, then renew async
            self._election_stop.clear()
            self._election_thread = threading.Thread(
                target=self._election_loop, daemon=True
            )
            self._election_thread.start()
        return self._httpd.server_address[1]

    def _election_loop(self) -> None:
        # renew at a third of the lease duration, the same ratio as
        # client-go's RenewDeadline/LeaseDuration defaults
        period = max(self.elector.lease.duration / 3.0, 0.05)
        while not self._election_stop.wait(period):
            try:
                self.elector.tick()
            except Exception:  # noqa: BLE001 — a transient IO error on
                # the lease volume must not kill the election loop (the
                # lease would then silently lapse / never be contended)
                pass

    def stop(self, before_release=None) -> None:
        """Shut down in write-safe order: stop accepting requests
        FIRST, then run ``before_release`` (the final state checkpoint),
        then release the lease — so a standby can only take over after
        the checkpoint it will reload from is fully on disk."""
        self._stopping.set()  # unpark watch long-polls / SSE tails
        if self._tls_rotation_thread is not None:
            self._tls_rotation_stop.set()
            self._tls_rotation_thread.join(timeout=5)
            self._tls_rotation_thread = None
        if self.tls is not None and hasattr(self.tls, "reload_hooks"):
            try:
                self.tls.reload_hooks.remove(self._load_certs)
            except ValueError:
                pass
        if self._election_thread is not None:
            self._election_stop.set()
            self._election_thread.join(timeout=5)
            self._election_thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.gateway is not None:
            # after the HTTP drain: whatever the gateway still queues
            # belongs to already-answered or dropped connections; the
            # stop() flush applies it before the final checkpoint
            self.gateway.stop()
        if before_release is not None:
            before_release()
        if self.elector is not None:
            self.elector.step_down()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port


# route names gated by KueueServer.auth_token (when configured)
_SECURED_ROUTES = frozenset(
    {
        "apply", "apply_batch", "delete", "delete_ns", "check_state",
        "reconcile", "solve", "metrics", "state", "debug_cycles",
        "workload_decisions", "plan", "quarantine_list", "quarantine_clear",
        # the replication feed serializes every state mutation — gate
        # it exactly like /state
        "journal_tail",
        # trace stores expose workload identities + timing: same gate
        # as the decision audit surface
        "debug_traces", "debug_trace_get", "workload_trace",
        # dynamic membership mutates the dispatch roster (drain moves
        # real placements) — gate like every other write
        "federation_add_worker", "federation_remove_worker",
        "federation_membership",
    }
)

# mutating routes a read replica refuses: 307 to the leader, method and
# body preserved (kueuectl and KueueClient follow it transparently).
# NOT here: "solve" (stateless compute over a posted snapshot) and
# "plan" (read-only what-if over the replayed state — best-effort-stale
# by design, documented in deploy/README).
_REPLICA_REDIRECTED = frozenset(
    {
        "apply", "apply_batch", "delete", "delete_ns", "check_state",
        "reconcile", "quarantine_clear",
    }
)

_ROUTES: List[Tuple[str, re.Pattern, str]] = [
    ("GET", re.compile(r"^/healthz$"), "healthz"),
    ("GET", re.compile(r"^/readyz$"), "readyz"),
    ("GET", re.compile(r"^/metrics$"), "metrics"),
    (
        "GET",
        re.compile(
            r"^/apis/visibility/v1beta1/clusterqueues/([^/]+)/pendingworkloads$"
        ),
        "vis_cq",
    ),
    (
        "GET",
        re.compile(
            r"^/apis/visibility/v1beta1/namespaces/([^/]+)/localqueues/([^/]+)/pendingworkloads$"
        ),
        "vis_lq",
    ),
    (
        "POST",
        re.compile(r"^/apis/kueue/v1beta1/workloads/([^/]+)/([^/]+)/admissionchecks$"),
        "check_state",
    ),
    # literal routes FIRST: the generic section pattern below would
    # swallow "journal"/"replicas"/"slo" as object listings
    ("GET", re.compile(r"^/apis/kueue/v1beta1/journal$"), "journal_tail"),
    ("GET", re.compile(r"^/apis/kueue/v1beta1/replicas$"), "replicas"),
    ("GET", re.compile(r"^/apis/kueue/v1beta1/slo$"), "slo"),
    ("GET", re.compile(r"^/apis/kueue/v1beta1/([a-z]+)$"), "list"),
    (
        "GET",
        re.compile(r"^/apis/kueue/v1beta1/([a-z]+)/([^/]+)/([^/]+)$"),
        "get_ns",
    ),
    ("GET", re.compile(r"^/apis/kueue/v1beta1/([a-z]+)/([^/]+)$"), "get"),
    ("POST", re.compile(r"^/apis/kueue/v1beta1/batch$"), "apply_batch"),
    ("POST", re.compile(r"^/apis/kueue/v1beta1/([a-z]+)$"), "apply"),
    (
        "DELETE",
        re.compile(r"^/apis/kueue/v1beta1/(workloads)/([^/]+)/([^/]+)$"),
        "delete_ns",
    ),
    (
        "DELETE",
        re.compile(r"^/apis/kueue/v1beta1/(clusterqueues|resourceflavors|nodes)/([^/]+)$"),
        "delete",
    ),
    (
        "GET",
        re.compile(
            r"^/apis/kueue/v1beta1/localqueues/([^/]+)/([^/]+)/status$"
        ),
        "lq_status",
    ),
    (
        "GET",
        re.compile(r"^/apis/federation/v1beta1/clusters$"),
        "federation_clusters",
    ),
    (
        "GET",
        re.compile(r"^/apis/federation/v1beta1/status$"),
        "federation_status",
    ),
    (
        "POST",
        re.compile(r"^/apis/federation/v1beta1/clusters$"),
        "federation_add_worker",
    ),
    (
        "POST",
        re.compile(
            r"^/apis/federation/v1beta1/clusters/([^/]+)/(cordon|uncordon|drain)$"
        ),
        "federation_membership",
    ),
    (
        "DELETE",
        re.compile(r"^/apis/federation/v1beta1/clusters/([^/]+)$"),
        "federation_remove_worker",
    ),
    ("GET", re.compile(r"^/apis/elastic/v1beta1/capacity$"), "capacity"),
    ("GET", re.compile(r"^/global/standings$"), "global_standings"),
    ("POST", re.compile(r"^/reconcile$"), "reconcile"),
    ("GET", re.compile(r"^/events/stream$"), "events_stream"),
    ("GET", re.compile(r"^/debug/cycles$"), "debug_cycles"),
    ("GET", re.compile(r"^/debug/traces$"), "debug_traces"),
    ("GET", re.compile(r"^/debug/traces/([^/]+)$"), "debug_trace_get"),
    (
        "GET",
        re.compile(r"^/debug/workloads/([^/]+)/([^/]+)/trace$"),
        "workload_trace",
    ),
    ("GET", re.compile(r"^/debug/quarantine$"), "quarantine_list"),
    ("POST", re.compile(r"^/debug/quarantine/clear$"), "quarantine_clear"),
    ("POST", re.compile(r"^/debug/plan$"), "plan"),
    (
        "GET",
        re.compile(r"^/debug/workloads/([^/]+)/([^/]+)/decisions$"),
        "workload_decisions",
    ),
    ("GET", re.compile(r"^/state$"), "state"),
    ("POST", re.compile(r"^/apis/solver/v1beta1/assign$"), "solve"),
    ("GET", re.compile(r"^/api/dashboard$"), "dashboard_json"),
    ("GET", re.compile(r"^/$"), "dashboard_html"),
]


def _make_handler(srv: KueueServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        # ---- dispatch ----
        def _dispatch(self, method: str):
            parsed = urlparse(self.path)
            query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
            for m, pat, name in _ROUTES:
                if m != method:
                    continue
                match = pat.match(parsed.path)
                if match:
                    try:
                        if (
                            srv.replica is not None
                            and name in _REPLICA_REDIRECTED
                        ):
                            # writes belong to the leader: 307 keeps
                            # method + body intact across the redirect
                            self._send_redirect(
                                srv.replica.leader_url + self.path
                            )
                            return
                        self._check_auth(name)
                        getattr(self, f"_h_{name}")(*match.groups(), **{"query": query})
                    except ApiError as e:
                        headers = None
                        if e.retry_after_s is not None:
                            # shed writes tell the client when to come
                            # back; KueueClient backs off on it
                            headers = {
                                "Retry-After": f"{e.retry_after_s:.3f}"
                            }
                        self._send_json(
                            {"error": e.message}, status=e.status,
                            headers=headers,
                        )
                    except Exception as e:  # noqa: BLE001 — surface as 500
                        self._send_json({"error": repr(e)}, status=500)
                    return
            self._send_json({"error": f"no route for {method} {parsed.path}"}, 404)

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

        def do_DELETE(self):
            self._dispatch("DELETE")

        # ---- helpers ----
        def _check_auth(self, route_name: str) -> None:
            if srv.auth_token is None or route_name not in _SECURED_ROUTES:
                return
            import hmac

            header = self.headers.get("Authorization", "")
            expect = f"Bearer {srv.auth_token}"
            if not hmac.compare_digest(header.encode(), expect.encode()):
                # the rejected request's body was never read: drain it
                # (and drop the connection) so a keep-alive client's
                # next request is not parsed out of the stale bytes
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    self.rfile.read(length)
                self.close_connection = True
                raise ApiError(401, "missing or invalid bearer token")

        def _body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            if length == 0:
                return {}
            raw = self.rfile.read(length)
            try:
                return json.loads(raw)
            except json.JSONDecodeError as e:
                raise ApiError(400, f"invalid JSON body: {e}")

        def _send_json(self, obj, status: int = 200, headers=None) -> None:
            payload = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            for name, value in (headers or {}).items():
                self.send_header(name, value)
            if srv.replica is not None:
                # every replica-served read is labeled with its role +
                # staleness so clients (kueuectl) can tell the user the
                # answer may trail the leader
                self.send_header("X-Kueue-Role", "replica")
                self.send_header(
                    "X-Kueue-Replica-Lag",
                    f"{srv.replica.tailer.lag_s:.3f}",
                )
            if self.close_connection:
                # tell keep-alive clients not to reuse the connection
                # (set by the auth rejection path)
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(payload)

        def _send_redirect(self, location: str) -> None:
            """307: same method + body at the leader. The unread
            request body is drained (and the connection dropped) so a
            keep-alive client's next request does not parse out of the
            stale bytes."""
            length = int(self.headers.get("Content-Length", 0))
            if length:
                self.rfile.read(length)
            self.close_connection = True
            payload = json.dumps(
                {"error": "read replica: writes are served by the leader",
                 "leader": location}
            ).encode()
            self.send_response(307)
            self.send_header("Location", location)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(payload)

        def _send_text(self, text: str, content_type: str, status: int = 200) -> None:
            payload = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        # ---- handlers ----
        def _h_healthz(self, query):
            body = {"status": "ok"}
            journal = getattr(srv.runtime, "journal", None)
            if journal is not None:
                st = journal.stats()
                # degraded persistence is a health DETAIL, not a
                # liveness failure: restarting the pod cannot fix a
                # full volume, so the probe stays 200 and the operator
                # pages on status/kueue_journal_degraded instead
                if st.degraded:
                    body["status"] = "degraded"
                body["persistence"] = {
                    "mode": "degraded" if st.degraded else "journaling",
                    "journalSegments": st.segments,
                    "journalBytes": st.bytes,
                    "journalReclaimedBytes": st.reclaimed_bytes,
                    "lastSeq": st.last_seq,
                    "droppedAppends": st.dropped_appends,
                    "lastError": st.last_error,
                    "lastFsyncAgeS": st.last_fsync_age_s,
                }
            # delta-checkpoint chain posture (storage/checkpoint.py):
            # same convention — a failing chain write (ENOSPC on the
            # state volume) flips "degraded" while the probe stays 200
            # (the previous chain is still valid; the operator pages
            # on kueue_checkpoint_degraded / this detail), and the
            # next successful checkpoint self-heals it
            ckpt = getattr(srv.runtime, "checkpointer", None)
            if ckpt is not None:
                detail = ckpt.status()
                body.setdefault("persistence", {})["checkpoint"] = detail
                if detail["degraded"]:
                    body["status"] = "degraded"
                    body["persistence"]["mode"] = "degraded"
            # solver-path detail (core/guard.py): same journal-degraded
            # convention — an open/quarantined device circuit or any
            # quarantined workload flips "degraded" while the probe
            # stays 200 (admission still runs, on the host mirror)
            guard = getattr(
                getattr(srv.runtime, "scheduler", None), "guard", None
            )
            if guard is not None:
                detail = guard.health()
                quarantine = getattr(srv.runtime, "quarantine", None)
                detail["quarantinedWorkloads"] = (
                    len(quarantine) if quarantine is not None else 0
                )
                body["solver"] = detail
                if guard.degraded or detail["quarantinedWorkloads"]:
                    body["status"] = "degraded"
            # active admission policy (kueue_tpu/policy): informational
            # — the dashboard badge and runbooks read it here
            policy = getattr(srv.runtime, "policy", None)
            if policy is not None:
                body["policy"] = policy.name
            # gateway serving tier (kueue_tpu/gateway): ingest posture
            # — queue depth, coalescing stats, per-reason shed counts
            if srv.gateway is not None:
                body["gateway"] = srv.gateway.status()
            # admission SLOs: attainment + burn per targeted CQ; a
            # SUSTAINED error-budget burn flips "degraded" while the
            # probe stays 200 (admission still runs — the operator
            # pages on kueue_slo_degraded / this detail)
            slo = getattr(srv.runtime, "slo", None)
            if slo is not None and slo.enabled:
                slo.maybe_refresh()
                detail = slo.report()
                body["slo"] = detail
                if detail["degraded"]:
                    body["status"] = "degraded"
            # federation detail (kueue_tpu/federation): same convention
            # — a lost, quarantined or gray (probation) worker cluster
            # flips "degraded" while the probe stays 200 (the
            # dispatcher keeps routing around it; the operator pages on
            # the detail / kueue_multikueue_clusters_active /
            # kueue_worker_health instead)
            fed = getattr(srv.runtime, "federation", None)
            if fed is not None:
                detail = fed.health_report()
                body["federation"] = detail
                if detail["degraded"]:
                    body["status"] = "degraded"
            # replication detail (kueue_tpu/replica): a replica reports
            # its staleness (appliedSeq, lagSeconds) here; a failing
            # tail flips "degraded" — the replica still serves its last
            # consistent state, so the probe stays 200 and the operator
            # pages on kueue_replica_lag_seconds / this detail
            if srv.replica is not None:
                detail = srv.replica.status()
                body["replication"] = detail
                if detail.get("lastError"):
                    body["status"] = "degraded"
            elif srv.replica_roster:
                from kueue_tpu.replica import replication_section

                detail = replication_section(srv.runtime)
                detail["replicas"] = len(srv.replica_roster)
                body["replication"] = detail
            self._send_json(body)

        def _h_readyz(self, query):
            # standby replicas are Ready (they serve reads) but report
            # their role so probes/operators can tell them apart
            body = {"status": "ok"}
            if srv.elector is not None:
                body["leader"] = srv.elector.is_leader
                body["holder"] = srv.elector.lease.holder()
                body["identity"] = srv.elector.identity
            self._send_json(body)

        def _h_metrics(self, query):
            slo = getattr(srv.runtime, "slo", None)
            if slo is not None:
                # scrape-time refresh so kueue_slo_* gauges are current
                slo.maybe_refresh()
            with srv.lock:
                text = srv.runtime.metrics.registry.expose()
            self._send_text(text, "text/plain; version=0.0.4")

        def _h_slo(self, query):
            """Admission-SLO standings (the `kueuectl slo` payload):
            per-ClusterQueue target, attainment ratio and error-budget
            burn rate over the configured window."""
            slo = getattr(srv.runtime, "slo", None)
            if slo is None:
                raise ApiError(404, "slo tracking is not available")
            slo.maybe_refresh()
            self._send_json(slo.report())

        def _int_param(self, query, key, default):
            try:
                return int(query.get(key, default))
            except ValueError:
                raise ApiError(400, f"query parameter {key!r} must be an integer")

        def _h_vis_cq(self, cq, query):
            offset = self._int_param(query, "offset", 0)
            limit = self._int_param(query, "limit", 1000)
            with srv.lock:
                summary = visibility.pending_workloads_in_cq(
                    srv.runtime.queues, cq, offset=offset, limit=limit,
                    audit=getattr(srv.runtime, "audit", None),
                )
            self._send_json(_summary_to_dict(summary))

        def _h_vis_lq(self, ns, lq, query):
            offset = self._int_param(query, "offset", 0)
            limit = self._int_param(query, "limit", 1000)
            with srv.lock:
                summary = visibility.pending_workloads_in_lq(
                    srv.runtime.queues, ns, lq, offset=offset, limit=limit,
                    audit=getattr(srv.runtime, "audit", None),
                )
            self._send_json(_summary_to_dict(summary))

        # section -> the event ``regarding.kind`` a watch on it filters
        # to ("events" itself is unfiltered). Today every emission site
        # regards a Workload; the map keeps the route shape K8s-true so
        # future kinds slot in without a new URL scheme.
        _REGARDING = {
            "events": None,
            "workloads": "Workload",
            "clusterqueues": "ClusterQueue",
            "localqueues": "LocalQueue",
        }

        def _h_list(self, section, query):
            if query.get("watch") in ("1", "true"):
                return self._watch(section, query)
            if section == "events":
                rec = srv.runtime.events
                items, _ = rec.since(
                    self._int_param(query, "resourceVersion", 0)
                )
                return self._send_json(
                    {"items": items, "resourceVersion": rec.resource_version}
                )
            self._send_json(srv.list_section(section))

        def _watch(self, section, query):
            """resourceVersion long-poll (the apiserver watch analog):
            blocks OUTSIDE srv.lock until the recorder stamps something
            newer than the client's resourceVersion, then returns the
            delta. 410 when the version fell out of the bounded ring —
            the client must relist and re-watch from the fresh head."""
            if section != "events" and section not in _SECTIONS:
                raise ApiError(404, f"unknown section {section!r}")
            regarding = self._REGARDING.get(
                section, section[:-1].capitalize()
            )
            rv = self._int_param(query, "resourceVersion", 0)
            try:
                timeout = min(float(query.get("timeoutSeconds", 30)), 300.0)
            except ValueError:
                raise ApiError(400, "timeoutSeconds must be a number")
            rec = srv.runtime.events
            items, latest, too_old = rec.wait(
                rv, timeout, regarding_kind=regarding,
                should_stop=srv._stopping.is_set,
            )
            if too_old:
                raise ApiError(
                    410,
                    f"resourceVersion {rv} is too old; relist and "
                    f"re-watch from {latest}",
                )
            self._send_json({"items": items, "resourceVersion": latest})

        def _h_events_stream(self, query):
            """Server-Sent-Events live tail of the event pipeline. Each
            frame's ``id:`` is the event's resourceVersion, so EventSource
            reconnects resume exactly where they dropped (Last-Event-ID);
            an ``event: reset`` frame tells the client its resume point
            fell out of the ring (the 410 analog mid-stream). Heartbeat
            comments every poll window keep proxies from reaping the
            connection and surface dead clients to the server."""
            rv = self._int_param(query, "resourceVersion", 0)
            last_id = self.headers.get("Last-Event-ID")
            if last_id:
                try:
                    rv = int(last_id)
                except ValueError:
                    pass
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.close_connection = True
            self.end_headers()
            rec = srv.runtime.events
            try:
                while not srv._stopping.is_set():
                    if srv.runtime.events is not rec:
                        # HA promotion swapped the runtime (and with it
                        # the recorder): restart from its head
                        rec = srv.runtime.events
                        rv = 0
                    items, latest, too_old = rec.wait(
                        rv, 15.0, should_stop=srv._stopping.is_set
                    )
                    if too_old:
                        self.wfile.write(b"event: reset\ndata: {}\n\n")
                    for item in items:
                        frame = (
                            f"id: {item['resourceVersion']}\n"
                            f"data: {json.dumps(item)}\n\n"
                        )
                        self.wfile.write(frame.encode())
                    if not items:
                        self.wfile.write(b": keep-alive\n\n")
                    self.wfile.flush()
                    rv = max(rv, latest)
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # client went away — the stream's normal ending

        def _h_get_ns(self, section, ns, name, query):
            self._send_json(srv.get_object(section, ns, name))

        def _h_lq_status(self, ns, name, query):
            with srv.lock:
                status = srv.runtime.local_queue_status(ns, name)
            if status is None:
                raise ApiError(404, f"localqueue {ns}/{name} not found")
            self._send_json(status)

        def _h_get(self, section, name, query):
            self._send_json(srv.get_object(section, "", name))

        def _propagate_traceparent(self, section, obj) -> None:
            """W3C trace-context over the HTTP plane: a ``traceparent``
            request header on a workload upsert lands as the
            traceparent label, so the receiving runtime JOINS the
            caller's trace instead of minting a fresh id (the
            kubeconfig-free analog of header propagation — labels
            survive serialization, journaling and replication)."""
            if section != "workloads" or not isinstance(obj, dict):
                return
            from kueue_tpu.tracing import TRACEPARENT_LABEL, parse_traceparent

            header = self.headers.get("traceparent")
            if parse_traceparent(header) is None:
                return
            labels = obj.setdefault("labels", {})
            labels.setdefault(TRACEPARENT_LABEL, header)

        def _throttled(self, e) -> ApiError:
            return ApiError(
                429, f"write shed ({e.reason}): {e}",
                retry_after_s=e.retry_after_s,
            )

        def _h_apply(self, section, query):
            body = self._body()
            self._propagate_traceparent(section, body)
            if srv.gateway is not None:
                # coalescing write path: enqueue (shed with 429 +
                # Retry-After when over budget) and wait for the flush
                # window that applies it
                from kueue_tpu.gateway import GatewayThrottled

                srv.require_leader()
                try:
                    obj = srv.gateway.submit(section, body)
                except GatewayThrottled as e:
                    raise self._throttled(e)
                except TimeoutError as e:
                    raise ApiError(503, str(e))
            else:
                obj = srv.apply(section, body)
            self._send_json({"applied": obj})

        def _h_apply_batch(self, query):
            body = self._body()
            for obj in body.get("workloads", []) or []:
                self._propagate_traceparent("workloads", obj)
            if srv.gateway is not None:
                from kueue_tpu.gateway import GatewayThrottled

                srv.require_leader()
                srv.validate_batch_body(body)
                try:
                    out = srv.gateway.submit_batch(body)
                except GatewayThrottled as e:
                    raise self._throttled(e)
                except TimeoutError as e:
                    raise ApiError(503, str(e))
            else:
                out = srv.apply_batch(body)
            self._send_json(out)

        def _h_delete_ns(self, section, ns, name, query):
            srv.delete(section, ns, name)
            self._send_json({"deleted": f"{ns}/{name}"})

        def _h_delete(self, section, name, query):
            srv.delete(section, "", name)
            self._send_json({"deleted": name})

        def _h_check_state(self, ns, name, query):
            body = self._body()
            srv.set_admission_check_state(
                ns,
                name,
                check=body.get("name", ""),
                state=body.get("state", ""),
                message=body.get("message", ""),
            )
            self._send_json({"updated": f"{ns}/{name}"})

        def _h_federation_clusters(self, query):
            """Worker-cluster roster + connectivity/guard state — the
            `kueuectl clusters list` payload. 404 when this control
            plane is not running a federation dispatcher."""
            fed = getattr(srv.runtime, "federation", None)
            if fed is None:
                raise ApiError(404, "federation is not enabled")
            with srv.lock:
                items = fed.cluster_report()
            self._send_json({"items": items})

        def _h_federation_status(self, query):
            """Full federation status: cluster roster, per-workload
            dispatch state (winner, fence), pending retractions."""
            fed = getattr(srv.runtime, "federation", None)
            if fed is None:
                raise ApiError(404, "federation is not enabled")
            with srv.lock:
                status = fed.status()
            self._send_json(status)

        def _h_federation_add_worker(self, query):
            """Runtime scale-up join: add a worker cluster to the
            dispatch roster without a restart. Body: {"name", "url",
            "token"?}. The worker is dispatchable on the next pass."""
            fed = getattr(srv.runtime, "federation", None)
            if fed is None:
                raise ApiError(404, "federation is not enabled")
            srv.require_leader()
            body = self._body()
            name = body.get("name") or ""
            url = body.get("url") or ""
            if not name or not url:
                raise ApiError(400, "body must carry name and url")
            from kueue_tpu.admissionchecks.multikueue import (
                MultiKueueCluster,
            )
            from kueue_tpu.admissionchecks.multikueue_transport import (
                HTTPTransport,
            )

            with srv.lock:
                fed.add_worker(
                    MultiKueueCluster(
                        name=name,
                        transport=HTTPTransport(
                            url, token=body.get("token") or None
                        ),
                    )
                )
            self._send_json({"joined": name})

        def _h_federation_membership(self, name, action, query):
            """cordon: stop new dispatches; uncordon: readmit; drain:
            cordon + move every placement off the worker under the
            fencing protocol (deposed winners re-dispatch elsewhere)."""
            fed = getattr(srv.runtime, "federation", None)
            if fed is None:
                raise ApiError(404, "federation is not enabled")
            srv.require_leader()
            with srv.lock:
                if action == "drain":
                    if name not in fed.clusters:
                        raise ApiError(404, f"unknown worker cluster {name!r}")
                    deposed = fed.drain_worker(name)
                    out = {"drained": name, "deposed": deposed}
                else:
                    ok = (
                        fed.cordon(name)
                        if action == "cordon"
                        else fed.uncordon(name)
                    )
                    if not ok:
                        raise ApiError(404, f"unknown worker cluster {name!r}")
                    out = {action + "ed": name}
            self._send_json(out)

        def _h_federation_remove_worker(self, name, query):
            """Scale-down leave: drain, flush retractions, drop the
            worker from the roster."""
            fed = getattr(srv.runtime, "federation", None)
            if fed is None:
                raise ApiError(404, "federation is not enabled")
            srv.require_leader()
            with srv.lock:
                if not fed.remove_worker(name):
                    raise ApiError(404, f"unknown worker cluster {name!r}")
            self._send_json({"removed": name})

        def _h_capacity(self, query):
            """Elastic capacity plane status: provider grants, applied
            (journaled) requests, in-flight asks, last chooser verdict.
            404 when --elastic is off."""
            plane = getattr(srv.runtime, "elastic", None)
            if plane is None:
                raise ApiError(404, "elastic capacity plane is not enabled")
            with srv.lock:
                body = plane.status()
            self._send_json(body)

        def _h_global_standings(self, query):
            """Federation-wide visibility: the global scheduler's
            read-only rescore — per-worker standings + every pending
            workload's per-cluster forecast and best placement. 404
            when this plane runs no global scheduler."""
            fed = getattr(srv.runtime, "federation", None)
            gs = (
                getattr(fed, "global_scheduler", None)
                if fed is not None
                else None
            )
            if gs is None:
                raise ApiError(404, "global scheduler is not enabled")
            with srv.lock:
                body = gs.standings()
            self._send_json(body)

        def _h_reconcile(self, query):
            srv.require_leader()
            with srv.lock:
                cycles = srv.runtime.run_until_idle()
            self._send_json({"cycles": cycles})

        def _h_debug_cycles(self, query):
            # per-cycle phase attribution (the pprof-ish surface)
            with srv.lock:
                traces = [
                    t.to_dict() for t in srv.runtime.scheduler.last_traces
                ]
            self._send_json({"cycles": traces})

        def _h_debug_traces(self, query):
            """Bounded in-memory trace store: newest traces first
            (id, root span, span count, duration)."""
            tracer = getattr(srv.runtime, "tracer", None)
            limit = self._int_param(query, "limit", 64)
            with srv.lock:
                items = (
                    tracer.traces_summary(limit) if tracer is not None else []
                )
            self._send_json({"items": items})

        def _h_debug_trace_get(self, trace_id, query):
            """One full span tree."""
            tracer = getattr(srv.runtime, "tracer", None)
            with srv.lock:
                spans = (
                    [s.to_dict() for s in tracer.trace(trace_id)]
                    if tracer is not None
                    else []
                )
            if not spans:
                raise ApiError(404, f"trace {trace_id} not found")
            self._send_json({"traceId": trace_id, "spans": spans})

        def _h_workload_trace(self, ns, name, query):
            """The workload's lifecycle trace plus every cycle trace
            its decisions reference — the `kueuectl trace` payload
            (Chrome-trace exportable)."""
            from kueue_tpu.tracing import workload_trace_payload

            key = f"{ns}/{name}"
            with srv.lock:
                payload = workload_trace_payload(srv.runtime, key)
                known = key in srv.runtime.workloads
            if not payload["spans"] and not known:
                raise ApiError(404, f"workload {key} not found")
            self._send_json(payload)

        def _h_quarantine_list(self, query):
            """Poison-workload quarantine triage (kueuectl quarantine
            list): sidelined workloads + the solver guard's state."""
            with srv.lock:
                items = srv.runtime.quarantine_report()
                guard = getattr(srv.runtime.scheduler, "guard", None)
                solver = guard.health() if guard is not None else {}
            self._send_json({"items": items, "solver": solver})

        def _h_quarantine_clear(self, query):
            """Release one (body: {"workload": "ns/name"}) or every
            (empty body) quarantined workload back to nomination —
            ``kueuectl quarantine clear`` / the manual requeue."""
            srv.require_leader()
            body = self._body()
            with srv.lock:
                cleared = srv.runtime.clear_quarantine(
                    body.get("workload") or None
                )
                if srv.auto_reconcile and cleared:
                    srv.runtime.run_until_idle()
            self._send_json({"cleared": cleared})

        def _h_plan(self, query):
            """What-if capacity planner. Leader-only in elector HA (a
            checkpoint-refresh standby's state can lag by the whole
            checkpoint period, so plans there would be confidently
            wrong) — but a journal-tailing READ REPLICA serves it:
            its state trails by one poll interval, the response carries
            the X-Kueue-Replica-Lag header, and the semantics are
            documented best-effort-stale (deploy/README "Read
            replicas"). Strictly read-only over the runtime
            (guardrail-tested: state dump and event resourceVersion are
            byte-identical across a plan call)."""
            if srv.replica is None:
                srv.require_leader()
            from kueue_tpu.planner import plan_request
            from kueue_tpu.planner.scenarios import ScenarioApplyError

            body = self._body()
            with srv.lock:
                try:
                    report = plan_request(srv.runtime, body)
                except (ScenarioApplyError, KeyError, ValueError) as e:
                    raise ApiError(400, f"invalid plan request: {e}")
            self._send_json(report)

        def _h_workload_decisions(self, ns, name, query):
            """Per-workload decision audit trail (oldest first). 404
            only when the workload is unknown AND left no trail — a
            just-deleted workload's history stays readable until the
            audit ring forgets it."""
            key = f"{ns}/{name}"
            with srv.lock:
                audit = getattr(srv.runtime, "audit", None)
                items = visibility.workload_decisions(audit, key)
                known = key in srv.runtime.workloads
            if not items and not known:
                raise ApiError(404, f"workload {key} not found")
            self._send_json({"workload": key, "items": items})

        def _h_state(self, query):
            with srv.lock:  # snapshot under lock; write to client outside
                state = ser.runtime_to_state(srv.runtime)
                if srv.replica is not None:
                    # the replica has no journal attached, so stamp its
                    # APPLIED position instead of journalSeq=0 — at
                    # quiescence this makes the replica's dump
                    # byte-identical to the leader's (the convergence
                    # acceptance check). The fence rides along so a
                    # downstream tailer anchoring on THIS node's state
                    # (fan-out trees) adopts the leader's token.
                    state["persistence"]["journalSeq"] = (
                        srv.replica.tailer.applied_seq
                    )
                    state["persistence"]["token"] = (
                        srv.replica.tailer.max_token
                    )
            self._send_json(state)

        def _h_journal_tail(self, query):
            """The replication feed read replicas poll: journal records
            past ``sinceSeq``, bundled with the event-recorder and
            audit-log deltas so one round trip per poll interval keeps
            every replica read surface current. Registers the polling
            replica in the roster. On the LEADER the segment scan runs
            OUTSIDE srv.lock — segments are append-only, the CRC
            framing makes a concurrently half-written tail frame
            invisible, and holding the serving lock for an O(delta)
            file scan would put reads back on the admission hot path.
            On a REPLICA the same contract is served from the tailer's
            bounded in-memory feed log — replicas tail replicas
            (``--replica-of`` pointed at another replica), so watch/SSE
            load fans out in a tree instead of all replicas hammering
            the leader; the response's ``hop``/``pathLag`` fields let
            downstream nodes report their distance and per-hop
            staleness."""
            since = self._int_param(query, "sinceSeq", 0)
            limit = max(1, min(self._int_param(query, "limit", 2048), 65536))
            if srv.replica is not None:
                tailer = srv.replica.tailer
                with srv.lock:
                    applied = tailer.applied_seq
                    feed = [
                        rec for rec in tailer.feed_log if rec.seq > since
                    ]
                    first_available = (
                        tailer.feed_log[0].seq
                        if tailer.feed_log
                        else applied + 1
                    )
                    token = tailer.max_token
                body = {
                    "lastSeq": applied,
                    "firstAvailableSeq": first_available,
                    "token": token,
                    "leaderTime": srv.clock.now(),
                    "hop": tailer.hop,
                    "pathLag": tailer.path_lag(),
                }
                if since + 1 < first_available and applied > since:
                    # trimmed feed log or post-resync anchor: the
                    # downstream must re-anchor on OUR checkpoint
                    # (GET /state stamps appliedSeq + fence) — the
                    # leader-compaction contract, one hop down
                    body["compacted"] = True
                    body["records"] = []
                else:
                    body["compacted"] = False
                    body["records"] = [r.to_dict() for r in feed[:limit]]
            else:
                journal = getattr(srv.runtime, "journal", None)
                if journal is None:
                    raise ApiError(
                        404,
                        "no journal attached; replicas tail a leader "
                        "started with --journal (or another replica)",
                    )
                first_available = journal.first_available_seq()
                body = {
                    "lastSeq": journal.last_seq,
                    "firstAvailableSeq": first_available,
                    "token": (
                        journal.token_provider()
                        if journal.token_provider is not None
                        else None
                    ),
                    "leaderTime": srv.clock.now(),
                    "hop": 0,
                    "pathLag": [],
                }
                if since + 1 < first_available and journal.last_seq > since:
                    # the requested prefix was compacted away: the
                    # replica must re-anchor on a checkpoint (GET
                    # /state) — sending records with a hole would
                    # corrupt its replay
                    body["compacted"] = True
                    body["records"] = []
                else:
                    body["compacted"] = False
                    # offset-cursor tail: a caught-up replica's repeat
                    # poll reads O(delta) bytes, not the whole segment
                    body["records"] = [
                        rec.to_dict()
                        for rec in journal.tail_records(since, limit=limit)
                    ]
            # event + audit deltas (rv/seq-addressed, recorder-locked)
            ev_rv = self._int_param(query, "sinceEventRv", 0)
            rec_events = srv.runtime.events
            items, too_old = rec_events.since(ev_rv)
            body["events"] = items
            body["eventsRv"] = rec_events.resource_version
            body["eventsTooOld"] = too_old
            audit = getattr(srv.runtime, "audit", None)
            audit_seq = self._int_param(query, "sinceAuditSeq", 0)
            body["audit"] = audit.since(audit_seq) if audit is not None else []
            body["auditSeq"] = audit.seq if audit is not None else 0
            # span delta (kueue_tpu/tracing): replicas render the
            # LEADER's waterfalls, so the feed ships every span stamped
            # since the replica's cursor alongside events/audit
            tracer = getattr(srv.runtime, "tracer", None)
            span_seq = self._int_param(query, "sinceSpanSeq", 0)
            body["spans"] = (
                tracer.since(span_seq) if tracer is not None else []
            )
            body["spansSeq"] = tracer.seq if tracer is not None else 0
            replica_id = query.get("replica")
            if replica_id:
                try:
                    applied = int(query.get("appliedSeq", since))
                    lag = float(query.get("lagSeconds", 0.0))
                    hop = int(query.get("hop", body["hop"] + 1))
                except ValueError:
                    applied, lag = since, 0.0
                    hop = body["hop"] + 1
                srv.replica_roster[replica_id] = {
                    "id": replica_id,
                    "appliedSeq": applied,
                    "lagSeconds": lag,
                    "hop": hop,
                    "lastSeen": body["leaderTime"],
                }
            self._send_json(body)

        def _roster_items(self, head_seq: int) -> list:
            now = srv.clock.now()
            items = []
            for entry in sorted(
                srv.replica_roster.values(), key=lambda e: e["id"]
            ):
                item = dict(entry)
                item["lastSeenAgoS"] = round(now - entry["lastSeen"], 3)
                item["behind"] = max(0, head_seq - entry["appliedSeq"])
                items.append(item)
            return items

        def _h_replicas(self, query):
            """Follower roster (leader) / own status + downstream
            children (replica) — the ``kueuectl replicas`` payload.
            In a fan-out tree every node serves this: the leader lists
            its hop-1 followers, each mid-tier replica lists its own
            tail status plus the hop-(n+1) nodes tailing IT."""
            if srv.replica is not None:
                out = {
                    "role": "replica",
                    "items": [srv.replica.status()],
                }
                if srv.replica_roster:
                    out["children"] = self._roster_items(
                        srv.replica.tailer.applied_seq
                    )
                self._send_json(out)
                return
            journal = getattr(srv.runtime, "journal", None)
            head = journal.last_seq if journal is not None else 0
            self._send_json(
                {
                    "role": "leader",
                    "lastSeq": head,
                    "items": self._roster_items(head),
                }
            )

        def _h_solve(self, query):
            # stateless: deliberately NOT under srv.lock — solving a
            # posted snapshot doesn't touch the live runtime
            self._send_json(solve_assign(self._body()))

        def _h_dashboard_json(self, query):
            from kueue_tpu.server.dashboard import dashboard_payload

            with srv.lock:
                payload = dashboard_payload(srv.runtime)
            self._send_json(payload)

        def _h_dashboard_html(self, query):
            from kueue_tpu.server.dashboard import DASHBOARD_HTML

            self._send_text(DASHBOARD_HTML, "text/html")

    return Handler


def _summary_to_dict(summary: visibility.PendingWorkloadsSummary) -> dict:
    return {
        "items": [
            {
                "name": pw.name,
                "namespace": pw.namespace,
                "localQueueName": pw.local_queue_name,
                "priority": pw.priority,
                "positionInClusterQueue": pw.position_in_cluster_queue,
                "positionInLocalQueue": pw.position_in_local_queue,
                "inadmissibleReason": pw.inadmissible_reason,
                "message": pw.message,
                "lastCycle": pw.last_cycle,
            }
            for pw in summary.items
        ]
    }
