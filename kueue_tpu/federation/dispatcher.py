"""The federation dispatcher — partition-tolerant multi-cluster
dispatch with cross-cluster fencing and a journaled retraction
protocol.

Shape of one workload's life, N worker clusters:

1. **Rank.** The planner scores every healthy cluster by forecast
   time-to-admission (placement.py); the dispatcher mirrors copies to
   the top ``fanout`` clusters. The dispatch intent — epoch fence +
   target set — is journaled BEFORE the first wire call (WAL), so a
   dispatcher killed mid-dispatch replays the record and re-probes
   idempotently (create only where no copy exists).
2. **Race.** Each worker is a full control plane admitting on its own;
   the first cluster observed holding a quota reservation with the
   CURRENT fence echoed in its copy's labels wins. The winner pick is
   journaled; every loser gets a retraction.
3. **Retract.** Retractions are dedup-keyed (workload, cluster, fence),
   journaled on enqueue AND on ack, and retried at-least-once until the
   target acknowledges (a 404 — copy already gone — IS the ack, which
   is what makes retries idempotent). A retraction lost to a partition
   therefore cannot leave a gang admitted twice: the intent survives
   in the journal and in memory until the partition heals.
4. **Fence.** A winner lost past ``worker_lost_timeout`` is deposed:
   the fence bumps, the workload re-dispatches to the remaining
   clusters, and a retraction against the old winner is queued. When
   the deposed winner heals, its copy still carries the OLD fence —
   every sync-back echoes the fence, stale tokens are refused, and the
   stale copy is retracted instead of counting as an admission.
5. **Sync.** The winner's reservation/admission/finish flow back onto
   the local workload; finish triggers retract-everywhere GC.

Per-cluster failure handling rides the existing ``RemoteClient``
backoff machinery (now jittered); a cluster deposed repeatedly is
quarantined from NEW dispatches for ``cluster_quarantine_ttl_s`` — the
guard/quarantine pattern of core/guard.py applied to remotes.
Retractions still pump to a quarantined cluster: the fence cleanup must
reach a deposed winner the moment it heals.
"""

from __future__ import annotations

import time as _time
import zlib
from copy import deepcopy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from kueue_tpu.admissionchecks.multikueue import MultiKueueCluster
from kueue_tpu.admissionchecks.multikueue_transport import (
    ORIGIN_LABEL,
    ClusterUnreachable,
    RemoteClient,
    RemoteRejected,
    TransportError,
)
from kueue_tpu.federation.health import DEGRADED, HealthPlane
from kueue_tpu.models import Workload
from kueue_tpu.models.constants import WorkloadConditionType
from kueue_tpu.testing import faults

#: operations safe to hedge: reads and heartbeats are pure, copy-create
#: is absorbed by name+fence dedup on the worker, and delete is already
#: at-least-once with 404==ack — but delete rides the retraction pump's
#: own retry loop, so hedging it buys nothing
HEDGEABLE_OPS = frozenset(
    {"get_workload", "list_workload_keys", "create_workload",
     "create_workloads"}
)

#: fence epoch stamped into every mirrored copy's labels and echoed in
#: every sync-back — the cross-cluster split-brain guard
FENCE_LABEL = "kueue.x-k8s.io/multikueue-fence"
#: set on the LOCAL workload once a winner is picked (kueuectl explain
#: and `kueuectl clusters list` read it)
WINNER_LABEL = "kueue.x-k8s.io/multikueue-winner"
#: gang co-placement id (the JobSet/gang parent's key): members share
#: a rotation offset (same starting cluster), are mirrored with the
#: label intact over the wire, and a deposed winner's gang children
#: are retracted atomically with the member that tripped the deposal
GANG_LABEL = "kueue.x-k8s.io/multikueue-gang"

# journal record vocabulary (replayed by storage.recovery into
# runtime.federation_replay, consumed by FederationDispatcher.restore)
DISPATCH_RECORD = "federation_dispatch"
WINNER_RECORD = "federation_winner"
RETRACT_ENQUEUE_RECORD = "federation_retract_enqueue"
RETRACT_DONE_RECORD = "federation_retract_done"
FEDERATION_RECORD_TYPES = (
    DISPATCH_RECORD,
    WINNER_RECORD,
    RETRACT_ENQUEUE_RECORD,
    RETRACT_DONE_RECORD,
)


@dataclass
class DispatchState:
    """One workload's federation epoch."""

    key: str
    fence: int = 0  # 0 = never dispatched; first epoch is 1
    clusters: List[str] = field(default_factory=list)  # ranked targets
    mirrored: Set[str] = field(default_factory=set)  # confirmed copies
    winner: Optional[str] = None
    finished: bool = False

    def to_dict(self) -> dict:
        return {
            "workload": self.key,
            "fence": self.fence,
            "clusters": list(self.clusters),
            "mirrored": sorted(self.mirrored),
            "winner": self.winner,
            "finished": self.finished,
        }


@dataclass
class Retraction:
    """One at-least-once remote delete. The dedup key (workload,
    cluster, fence) makes re-enqueue idempotent across journal replay
    and across the sync loop re-discovering the same loser."""

    key: str
    cluster: str
    fence: int
    attempts: int = 0
    acked: bool = False

    @property
    def dedup(self) -> Tuple[str, str, int]:
        return (self.key, self.cluster, self.fence)

    def to_dict(self) -> dict:
        return {
            "workload": self.key,
            "cluster": self.cluster,
            "fence": self.fence,
            "attempts": self.attempts,
            "acked": self.acked,
        }


@dataclass
class ClusterHealth:
    """Per-remote guard state: strikes accumulate on deposals
    (worker_lost_timeout expiries), the threshold quarantines the
    cluster from NEW dispatches for a TTL."""

    strikes: int = 0
    quarantined_until: Optional[float] = None
    dispatches: int = 0
    wins: int = 0

    def quarantined(self, now: float) -> bool:
        return self.quarantined_until is not None and now < self.quarantined_until


class FederationDispatcher:
    def __init__(
        self,
        runtime,
        clusters: Optional[Dict[str, MultiKueueCluster]] = None,
        worker_lost_timeout: float = 900.0,
        fanout: Optional[int] = None,
        placement=None,  # callable(cluster, wl) -> score | None
        origin: str = "manager",
        base_backoff_s: float = 1.0,
        max_backoff_s: float = 300.0,
        cluster_quarantine_threshold: int = 3,
        cluster_quarantine_ttl_s: float = 600.0,
        heartbeat_interval_s: float = 30.0,
        drive_inprocess: bool = False,
        rank_cache: bool = True,
        adaptive_deadlines: bool = True,
        deadline_floor_s: float = 1.0,
        deadline_cap_s: float = 10.0,
        deadline_k: float = 3.0,
        hedging: bool = True,
        hedge_budget: float = 0.05,
        probe_deadline_s: float = 2.0,
        heartbeat_probe_budget: int = 1,
        health_plane_kw: Optional[dict] = None,
    ):
        from kueue_tpu.federation.placement import planner_placement_score

        self.runtime = runtime
        self.worker_lost_timeout = worker_lost_timeout
        self.fanout = fanout
        self.placement = placement or planner_placement_score
        self.origin = origin
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.cluster_quarantine_threshold = cluster_quarantine_threshold
        self.cluster_quarantine_ttl_s = cluster_quarantine_ttl_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self._last_contact: Dict[str, float] = {}
        # gray-failure immunity: the latency-aware health plane owns
        # per-worker RTT telemetry, the healthy→degraded→lost state
        # machine (probation), adaptive deadlines and the hedge budget
        self.adaptive_deadlines = adaptive_deadlines
        self.hedging = hedging
        self.probe_deadline_s = probe_deadline_s
        self.heartbeat_probe_budget = heartbeat_probe_budget
        self.worker_health = HealthPlane(
            runtime.clock,
            deadline_floor_s=deadline_floor_s,
            deadline_cap_s=deadline_cap_s,
            deadline_k=deadline_k,
            hedge_budget=hedge_budget,
            heartbeat_interval_s=heartbeat_interval_s,
            **(health_plane_kw or {}),
        )
        # in-process worker runtimes advance inside the manager's pass
        # (the analog of remote servers auto-reconciling on POST)
        self.drive_inprocess = drive_inprocess
        self.clusters: Dict[str, MultiKueueCluster] = {}
        self.states: Dict[str, DispatchState] = {}
        self.retractions: Dict[Tuple[str, str, int], Retraction] = {}
        self.health: Dict[str, ClusterHealth] = {}
        # dynamic membership (kueue_tpu/elastic): cordoned workers get
        # no NEW dispatches; drain_worker additionally retracts their
        # placements ahead of removal (scale-down drain-ahead)
        self.cordoned: Set[str] = set()
        # the global scheduler (federation/global_scheduler.py) attaches
        # itself here; every step() then runs its interval-gated rescore
        self.global_scheduler = None
        # per-step rank cache: (step_seq, health fingerprint, filtered
        # names, placement-score memo). Invalidated when the step
        # advances OR any cluster's connectivity/quarantine state flips
        # (a heartbeat marking a worker lost mid-step must re-filter)
        self.rank_cache = rank_cache
        self._step_seq = 0
        self._rank_memo: Optional[tuple] = None
        for cluster in (clusters or {}).values():
            self.add_cluster(cluster)
        # adopt journal records recovery replayed before we existed
        replay = getattr(runtime, "federation_replay", None)
        if replay:
            self.restore(replay)
            runtime.federation_replay = []
        runtime.federation = self

    # ---- wiring ----
    def add_cluster(self, cluster: MultiKueueCluster) -> None:
        if cluster.client is None:
            cluster.client = RemoteClient(
                cluster.transport,
                self.runtime.clock,
                base_backoff_s=self.base_backoff_s,
                max_backoff_s=self.max_backoff_s,
            )
        self.clusters[cluster.name] = cluster
        self.health.setdefault(cluster.name, ClusterHealth())
        m = getattr(self.runtime, "metrics", None)
        if m is not None:
            # pre-materialize this cluster's RTT series so the scrape
            # surface is complete before the first exchange
            m.multikueue_remote_rtt_seconds.touch(cluster=cluster.name)

    # ---- dynamic membership (scale-up join / drain-ahead scale-down) ----
    def _membership_metric(self, kind: str) -> None:
        m = getattr(self.runtime, "metrics", None)
        if m is not None:
            m.elastic_membership_changes_total.inc(kind=kind)

    def add_worker(self, cluster: MultiKueueCluster) -> None:
        """Runtime join: the worker becomes dispatchable on the next
        pass (rank-cache fingerprint changes with the cluster set)."""
        self.add_cluster(cluster)
        self.cordoned.discard(cluster.name)
        self._membership_metric("join")

    def cordon(self, name: str) -> bool:
        """Stop NEW dispatches to ``name``; existing placements stay
        (kubectl-cordon semantics — use drain_worker to move them)."""
        if name not in self.clusters:
            return False
        if name not in self.cordoned:
            self.cordoned.add(name)
            self.runtime.events.record(
                "ElasticWorkerCordoned", f"cluster/{name}",
                f"worker cluster {name} cordoned: no new dispatches; "
                "existing placements unaffected until drained",
                regarding_kind="Cluster",
            )
            self._membership_metric("cordon")
        return True

    def uncordon(self, name: str) -> bool:
        if name not in self.clusters:
            return False
        self.cordoned.discard(name)
        self._membership_metric("uncordon")
        return True

    def drain_worker(self, name: str) -> int:
        """Drain-ahead for scale-down: cordon ``name`` and move every
        placement off it under the fencing protocol — winners are
        deposed (fence bump + at-least-once retraction of the old
        epoch's copy + re-dispatch onto surviving capacity), pending
        mirrors are retracted and dropped from target sets. No strike:
        the operator chose this, the worker did nothing wrong. Returns
        how many placements were deposed."""
        if name not in self.clusters:
            return 0
        self.cordon(name)
        now = self.runtime.clock.now()
        deposed = 0
        for key in sorted(self.states):
            st = self.states[key]
            if st.finished:
                continue
            if st.winner == name:
                wl = self.runtime.workloads.get(key)
                if wl is None:
                    continue
                self._depose_winner(
                    wl, st, now,
                    f'worker cluster "{name}" draining for scale-down',
                    strike=False, cascade=False,
                )
                deposed += 1
            elif name in st.clusters or name in st.mirrored:
                if name in st.clusters:
                    st.clusters.remove(name)
                st.mirrored.discard(name)
                self._enqueue_retraction(key, name, st.fence)
        self._membership_metric("drain")
        return deposed

    def remove_worker(self, name: str) -> bool:
        """Scale-down: drain, flush retractions while the wire still
        exists, then drop the worker. Retractions that could not be
        delivered auto-ack on the next pump (the cluster left the
        federation — nothing to retract)."""
        if name not in self.clusters:
            return False
        self.drain_worker(name)
        self.pump_retractions()
        del self.clusters[name]
        self.health.pop(name, None)
        self.worker_health.forget(name)
        self.cordoned.discard(name)
        self._last_contact.pop(name, None)
        self._membership_metric("leave")
        return True

    # ---- journal plumbing (rides the PR-4 WAL) ----
    def _journal(self, rtype: str, data: dict) -> None:
        self.runtime._journal_append(rtype, data)

    def _trace_span(self, name: str, key: str, attrs: dict) -> None:
        """One federation hop on the workload's lifecycle trace."""
        tracer = getattr(self.runtime, "tracer", None)
        if tracer is not None:
            tracer.add_workload_span(name, key, attrs)

    def restore(self, records: List[tuple]) -> None:
        """Rebuild dispatch state from replayed journal records (in
        append order). Mirrors are NOT assumed to exist — the first
        pass after recovery re-probes every target and re-creates only
        where no copy answers, which is exactly the crash-mid-dispatch
        convergence story."""
        for rtype, data in records:
            key = data.get("key", data.get("workload", ""))
            if rtype == DISPATCH_RECORD:
                st = self.states.setdefault(key, DispatchState(key=key))
                if int(data["fence"]) >= st.fence:
                    st.fence = int(data["fence"])
                    st.clusters = list(data.get("clusters", []))
                    st.mirrored = set()
                    st.winner = None
            elif rtype == WINNER_RECORD:
                st = self.states.get(key)
                if st is not None and int(data["fence"]) == st.fence:
                    st.winner = data["cluster"]
            elif rtype == RETRACT_ENQUEUE_RECORD:
                r = Retraction(
                    key=key, cluster=data["cluster"], fence=int(data["fence"])
                )
                existing = self.retractions.get(r.dedup)
                if existing is None:
                    self.retractions[r.dedup] = r
                else:
                    # an enqueue AFTER an ack re-opens the entry (the
                    # copy was recreated under the same fence) — replay
                    # must land on the same at-least-once obligation
                    # the live dispatcher had
                    existing.acked = False
            elif rtype == RETRACT_DONE_RECORD:
                dedup = (key, data["cluster"], int(data["fence"]))
                r = self.retractions.get(dedup)
                if r is None:
                    r = Retraction(
                        key=key, cluster=data["cluster"],
                        fence=int(data["fence"]),
                    )
                    self.retractions[dedup] = r
                r.acked = True

    # ---- transport (timeout + backoff + fault surface) ----
    def _deadline_for(self, name: str, cap_s: Optional[float] = None):
        """Per-call adaptive deadline clamp(k*p99, floor, cap), or None
        (transport constructor default) when adaptive deadlines are
        off — the fixed-timeout baseline the grayfail bench A/Bs."""
        if not self.adaptive_deadlines:
            return None  # fixed-timeout baseline: constructor default
        return self.worker_health.deadline_s(name, cap_s=cap_s)

    def _hedge_for(self, name: str, op: str, deadline):
        """p95 hedge delay for idempotent ops, gated on the fleet-wide
        hedge budget; None disables hedging for this exchange."""
        if not self.hedging or op not in HEDGEABLE_OPS:
            return None
        hd = self.worker_health.hedge_delay_s(name)
        if hd is None or (deadline is not None and hd >= deadline):
            return None
        return hd

    def _report_hedge(self, cluster: MultiKueueCluster, m) -> None:
        outcome = cluster.client.last_hedge
        if outcome not in ("won", "lost"):
            return
        self.worker_health.record_hedge()
        if m is not None:
            m.report_hedge(outcome)

    def _call(
        self, cluster: MultiKueueCluster, op: str, *args,
        fault_point: str = "multikueue.partition",
        deadline_cap_s: Optional[float] = None,
    ):
        """One guarded wire exchange: the named fault point fires first
        (an armed TransportError models a partition on this wire and is
        charged to the cluster's reconnect state machine), then the
        call flows through the RemoteClient backoff gate under the
        adaptive per-call deadline (hedged for idempotent ops); every
        outcome lands in the kueue_multikueue_* metrics AND the
        latency-aware health plane.

        RTT is the max of the wall duration (perf_counter — the
        allowlisted telemetry timer) and the injected-clock delta: in
        production the two agree, under FakeClock chaos the injected
        latency only shows up on the clock — and the health plane must
        see the limp the chaos layer injected."""
        m = getattr(self.runtime, "metrics", None)
        deadline = self._deadline_for(cluster.name, deadline_cap_s)
        hedge = self._hedge_for(cluster.name, op, deadline)
        self.worker_health.record_call()
        t0 = _time.perf_counter()
        c0 = self.runtime.clock.now()
        try:
            try:
                faults.fire(fault_point)
            except TransportError as e:
                cluster.client._record_failure()
                raise ClusterUnreachable(str(e))
            result = cluster.client.call(
                op, *args, deadline_s=deadline, hedge_delay_s=hedge
            )
        except ClusterUnreachable:
            rtt = max(
                _time.perf_counter() - t0, self.runtime.clock.now() - c0
            )
            self._last_contact[cluster.name] = self.runtime.clock.now()
            self.worker_health.observe_rtt(cluster.name, rtt, ok=False)
            self._report_hedge(cluster, m)
            if m is not None:
                m.report_dispatch(cluster.name, "unreachable")
            raise
        except RemoteRejected:
            rtt = max(
                _time.perf_counter() - t0, self.runtime.clock.now() - c0
            )
            self._last_contact[cluster.name] = self.runtime.clock.now()
            # the wire answered — a rejection is a healthy exchange as
            # far as latency health is concerned
            self.worker_health.observe_rtt(cluster.name, rtt, ok=True)
            self._report_hedge(cluster, m)
            if m is not None:
                m.report_dispatch(cluster.name, "rejected", rtt)
            raise
        rtt = max(_time.perf_counter() - t0, self.runtime.clock.now() - c0)
        self._last_contact[cluster.name] = self.runtime.clock.now()
        self.worker_health.observe_rtt(cluster.name, rtt, ok=True)
        self._report_hedge(cluster, m)
        if m is not None:
            m.report_dispatch(cluster.name, "ok", rtt)
        return result

    # ---- placement ----
    def _health_fingerprint(self, now: float) -> tuple:
        """Connectivity + quarantine + latency-health state of every
        configured cluster — the rank cache's invalidation key. A
        heartbeat (or any wire exchange) that flips a cluster's
        reachability OR its probation state changes this fingerprint
        and drops the cached filtered list mid-step."""
        return tuple(
            (
                n,
                c.client.active if c.client is not None else True,
                self.health[n].quarantined(now),
                n in self.cordoned,
                self.worker_health.state(n),
            )
            for n, c in self.clusters.items()
        )

    def _healthy_names(self, now: float) -> List[str]:
        """The health-filtered cluster list, cached per federation step
        (rank_clusters used to rebuild it per WORKLOAD per step). The
        cache also scopes the per-(cluster, workload) placement-score
        memo: an invalidation drops both.

        Probation (latency-health DEGRADED) removes a worker from NEW
        dispatches the way quarantine does — but unlike quarantine it
        is latency-driven and self-clearing, and it falls back: if
        probation would leave NOTHING dispatchable, the degraded
        workers stay in rotation (a slow federation beats a stalled
        one)."""
        fp = self._health_fingerprint(now)
        if (
            not self.rank_cache
            or self._rank_memo is None
            or self._rank_memo[0] != self._step_seq
            or self._rank_memo[1] != fp
        ):
            eligible = [
                n for n, _active, quarantined, cordoned, _hs in fp
                if not quarantined and not cordoned
            ]
            preferred = [
                n for n, _active, quarantined, cordoned, hstate in fp
                if not quarantined and not cordoned and hstate != DEGRADED
            ]
            names = preferred or eligible
            self._rank_memo = (self._step_seq, fp, names, {})
        return self._rank_memo[2]

    def _placement_score(self, name: str, wl: Workload):
        """``self.placement`` through the per-step memo: within one
        step the same (cluster, workload) pair is forecast once even
        when dispatch and a deposal both rank it."""
        if not self.rank_cache or self._rank_memo is None:
            return self.placement(self.clusters[name], wl)
        memo = self._rank_memo[3]
        mkey = (name, wl.key)
        if mkey not in memo:
            memo[mkey] = self.placement(self.clusters[name], wl)
        return memo[mkey]

    def rank_clusters(self, wl: Workload) -> List[MultiKueueCluster]:
        """Healthy clusters, best placement first: planner-scored
        clusters ascending by forecast time-to-admission, then
        unscorable ones in a stable per-workload rotation (no
        structural favorite, same as the MultiKueue cluster scan).
        Gang members rotate on their shared gang id, so a gang's
        unscored tie-break starts every member on the SAME cluster."""
        now = self.runtime.clock.now()
        names = list(self._healthy_names(now))
        if len(names) > 1:
            spin = (wl.labels or {}).get(GANG_LABEL) or wl.key
            off = zlib.crc32(spin.encode()) % len(names)
            names = names[off:] + names[:off]
        scored: List[Tuple[float, int, str]] = []
        unscored: List[str] = []
        for i, name in enumerate(names):
            s = self._placement_score(name, wl)
            if s is None:
                unscored.append(name)
            else:
                scored.append((float(s), i, name))
        scored.sort()
        ordered = [name for _, _, name in scored] + unscored
        return [self.clusters[n] for n in ordered]

    # ---- the pass ----
    def step(self) -> None:
        """One federation pass (driven by ClusterRuntime.reconcile_once
        or the server's reconcile loop)."""
        faults.fire("multikueue.worker_crash")
        self._step_seq += 1
        now = self.runtime.clock.now()
        self._sweep_cluster_quarantine(now)
        self._heartbeat(now)
        self.pump_retractions()
        for key in sorted(self.runtime.workloads):
            self._reconcile(self.runtime.workloads[key], now)
        # a locally deleted workload's remote copies must not outlive
        # it: whatever the state still names gets a retraction. Already-
        # finished states are skipped — their retractions were enqueued
        # once and re-enqueueing every pass would re-open acked entries
        # (see _enqueue_retraction) and starve the finished-state GC
        for key in list(self.states):
            if key not in self.runtime.workloads:
                st = self.states[key]
                if st.finished:
                    continue
                for name in set(st.clusters) | st.mirrored:
                    self._enqueue_retraction(key, name, st.fence)
                st.finished = True
        self.pump_retractions()
        self._gc_states()
        if self.drive_inprocess:
            for cluster in self.clusters.values():
                rt = getattr(cluster.transport, "runtime", None)
                if rt is not None:
                    # a partitioned worker keeps scheduling on its own —
                    # only the wire is down — so this runs regardless of
                    # the connectivity state
                    rt.run_until_idle()
        if self.global_scheduler is not None:
            # the global rescore loop (federation/global_scheduler.py)
            # rides the federation pass: interval-gated, so most passes
            # pay one clock read
            self.global_scheduler.maybe_step()
        self._update_gauges()

    def _heartbeat(self, now: float) -> None:
        """Probe clusters the dispatch traffic hasn't touched lately —
        an idle loser must still be detected as lost so /healthz and
        kueue_multikueue_clusters_active tell the truth about the
        federation, not just about the wires the winners use.

        Heartbeats must never stall the dispatch step: each probe is
        bounded by ``probe_deadline_s`` (tighter than the full
        adaptive cap — a heartbeat carries no payload worth waiting
        for), and at most ``heartbeat_probe_budget`` probes per step
        go to NOT-active clusters (reconnect probes into a black hole
        each burn a full probe deadline; active-wire heartbeats are
        effectively free and stay unbudgeted)."""
        probes_left = self.heartbeat_probe_budget
        for name, cluster in self.clusters.items():
            last = self._last_contact.get(name, float("-inf"))
            if now - last < self.heartbeat_interval_s:
                continue
            if not cluster.client.reachable():
                continue
            if not cluster.client.active:
                if probes_left <= 0:
                    continue
                probes_left -= 1
            try:
                self._call(
                    cluster, "list_workload_keys", self.origin,
                    fault_point="multikueue.partition",
                    deadline_cap_s=self.probe_deadline_s,
                )
            except (ClusterUnreachable, RemoteRejected):
                continue

    def _reconcile(self, wl: Workload, now: float) -> None:
        st = self.states.get(wl.key)
        if wl.is_finished:
            if st is not None and not st.finished:
                self._finish_state(st)
            return
        if st is None or st.fence == 0:
            self._dispatch(wl, now)
            return
        if st.finished:
            return
        if st.winner is None:
            self._ensure_mirrors(wl, st)
            self._pick_winner(wl, st, now)
        else:
            self._sync_winner(wl, st, now)

    # ---- dispatch (mirror + WAL) ----
    def _dispatch(self, wl: Workload, now: float) -> None:
        order = self.rank_clusters(wl)
        targets = order[: self.fanout] if self.fanout else order
        if not targets:
            self._set_pending(
                wl, "no worker clusters available for dispatch", now
            )
            return
        st = DispatchState(
            key=wl.key, fence=1, clusters=[c.name for c in targets]
        )
        self.states[wl.key] = st
        # WAL: the intent is durable before the first wire call — a
        # crash anywhere past this line replays the record and
        # re-probes the same target set idempotently
        self._journal(
            DISPATCH_RECORD,
            {"key": st.key, "fence": st.fence, "clusters": st.clusters},
        )
        self._trace_span(
            "federation.dispatch", wl.key,
            {"clusters": list(st.clusters), "fence": st.fence},
        )
        self._set_pending(
            wl,
            "The workload is pending reservation in the worker clusters",
            now,
        )
        self._ensure_mirrors(wl, st)
        self._pick_winner(wl, st, now)

    def _remote_copy(self, wl: Workload, fence: int) -> Workload:
        labels = {ORIGIN_LABEL: self.origin, FENCE_LABEL: str(fence)}
        # gang/job sync adapter: the JobSet/gang parent id crosses the
        # wire with the copy, so a worker (or an operator reading it)
        # sees which mirrored workloads form one gang — and the
        # dispatcher's own sync-back can group them after a restart
        gang = (wl.labels or {}).get(GANG_LABEL)
        if gang:
            labels[GANG_LABEL] = gang
        # W3C trace-context propagation: the mirrored copy carries the
        # manager's lifecycle trace as a traceparent label, so the
        # winning worker's runtime JOINS that trace instead of minting
        # a fresh id — one trace spans manager, worker and replica
        tracer = getattr(self.runtime, "tracer", None)
        if tracer is not None:
            tid = tracer.workload_trace_id(wl.key)
            root = tracer.workload_root(wl.key)
            if tid is not None and root is not None:
                from kueue_tpu.tracing import (
                    TRACEPARENT_LABEL,
                    format_traceparent,
                )

                labels[TRACEPARENT_LABEL] = format_traceparent(
                    tid, root.span_id
                )
        return Workload(
            namespace=wl.namespace,
            name=wl.name,
            queue_name=wl.queue_name,
            pod_sets=deepcopy(wl.pod_sets),
            priority=wl.priority,
            priority_class_name=wl.priority_class_name,
            priority_class_source=wl.priority_class_source,
            creation_time=wl.creation_time,
            labels=labels,
        )

    def _retraction_outstanding(self, key: str, cluster: str) -> bool:
        return any(
            not r.acked
            for r in self.retractions.values()
            if r.key == key and r.cluster == cluster
        )

    def _ensure_mirrors(self, wl: Workload, st: DispatchState) -> None:
        for name in list(st.clusters):
            if name in st.mirrored:
                continue
            if self._retraction_outstanding(st.key, name):
                # retraction barrier: never create a copy while an
                # unacked delete is queued against the same (workload,
                # cluster) — the delete is by key and would otherwise
                # race the fresh copy away
                continue
            cluster = self.clusters.get(name)
            if cluster is None or not cluster.client.reachable():
                continue
            try:
                rwl = self._call(cluster, "get_workload", wl.key)
                if rwl is None:
                    self._call(
                        cluster, "create_workload",
                        self._remote_copy(wl, st.fence),
                    )
                    self.health[name].dispatches += 1
                else:
                    token = self._echoed_fence(rwl)
                    if token != st.fence:
                        # a previous epoch's copy: fence cleanup first,
                        # recreate after the retraction acks
                        self._enqueue_retraction(st.key, name, token)
                        continue
                st.mirrored.add(name)
            except ClusterUnreachable:
                continue
            except RemoteRejected as e:
                # the remote refused the object (its webhook chain):
                # per-workload, not connectivity — drop the target
                st.clusters.remove(name)
                self.runtime.event(
                    "MultiKueueRejected", wl, f"rejected by {name}: {e}"
                )

    # ---- winner pick + fencing ----
    def _echoed_fence(self, rwl: Workload) -> int:
        """The fence token a remote copy echoes back in its labels;
        the transform point models a corrupted/stale echo."""
        try:
            token = int(rwl.labels.get(FENCE_LABEL, 0) or 0)
        except (TypeError, ValueError):
            token = 0
        return int(faults.transform("multikueue.stale_token", token))

    def _pick_winner(self, wl: Workload, st: DispatchState, now: float) -> None:
        reserving: List[str] = []
        for name in st.clusters:
            cluster = self.clusters.get(name)
            if cluster is None or not cluster.client.reachable():
                continue
            try:
                rwl = self._call(cluster, "get_workload", wl.key)
            except (ClusterUnreachable, RemoteRejected):
                continue
            if rwl is None:
                st.mirrored.discard(name)
                continue
            token = self._echoed_fence(rwl)
            if token != st.fence:
                # stale fence: refuse the copy, queue its cleanup
                self._enqueue_retraction(st.key, name, token)
                st.mirrored.discard(name)
                continue
            if rwl.has_quota_reservation:
                reserving.append(name)
        if not reserving:
            return
        # the duplicate-admission window: >1 cluster may hold a
        # reservation right now; a crash here must recover to exactly
        # one admission (the winner record below is what closes it)
        faults.fire("multikueue.duplicate_admit")
        winner = reserving[0]
        st.winner = winner
        self._journal(
            WINNER_RECORD,
            {"key": st.key, "cluster": winner, "fence": st.fence},
        )
        self.health[winner].wins += 1
        wl.labels[WINNER_LABEL] = winner
        self._trace_span(
            "federation.winner", st.key,
            {"cluster": winner, "fence": st.fence},
        )
        self.runtime.event(
            "MultiKueueReserved", wl,
            f'The workload got reservation on "{winner}" (fence {st.fence})',
        )
        for name in st.clusters:
            if name != winner:
                self._enqueue_retraction(st.key, name, st.fence)
                st.mirrored.discard(name)
        st.clusters = [winner]
        self._sync_winner(wl, st, now)

    # ---- winner sync-back ----
    def _sync_winner(self, wl: Workload, st: DispatchState, now: float) -> None:
        # a crash between the winner record and the loser retractions
        # replays to a state where losers are still listed: re-derive
        # the retractions here (dedup-keyed, so steady state no-ops)
        for name in list(st.clusters):
            if name != st.winner:
                self._enqueue_retraction(st.key, name, st.fence)
                st.clusters.remove(name)
        cluster = self.clusters.get(st.winner or "")
        if cluster is None:
            self._depose_winner(wl, st, now, "winner cluster removed")
            return
        rwl = None
        got_answer = False
        if cluster.client.reachable():
            try:
                rwl = self._call(cluster, "get_workload", wl.key)
                got_answer = True
            except (ClusterUnreachable, RemoteRejected):
                pass
        if not got_answer:
            lost_for = (
                now - cluster.lost_since
                if cluster.lost_since is not None
                else 0.0
            )
            if lost_for >= self.worker_lost_timeout:
                self._depose_winner(
                    wl, st, now,
                    f"worker cluster {st.winner} lost for {lost_for:.0f}s",
                )
            return
        if rwl is None:
            # the winner's copy vanished (remote GC / operator delete):
            # restart the epoch
            self._depose_winner(wl, st, now, "remote copy lost")
            return
        token = self._echoed_fence(rwl)
        if token != st.fence:
            # split-brain guard: the copy answering for the winner
            # carries a stale fence — refuse it and retract
            self._enqueue_retraction(st.key, st.winner, token)
            self._depose_winner(
                wl, st, now,
                f"stale fencing token {token} (expected {st.fence})",
                strike=False,
            )
            return
        if rwl.is_finished:
            fin = rwl.conditions[WorkloadConditionType.FINISHED]
            wl.set_condition(
                WorkloadConditionType.FINISHED, True, fin.reason, fin.message,
                now=now,
            )
            self.runtime.on_workload_finished(wl)
            self._finish_state(st)
            return
        if rwl.has_quota_reservation:
            if not wl.has_quota_reservation:
                self._trace_span(
                    "federation.sync_back", st.key,
                    {"cluster": st.winner, "fence": st.fence,
                     "observed": "QuotaReserved"},
                )
                wl.set_condition(
                    WorkloadConditionType.QUOTA_RESERVED, True,
                    reason="QuotaReserved",
                    message=f'Quota reserved on cluster "{st.winner}"',
                    now=now,
                )
                self.runtime.event(
                    "QuotaReserved", wl,
                    f'Quota reserved on cluster "{st.winner}"',
                )
            if rwl.is_admitted and not wl.is_admitted:
                wl.set_condition(
                    WorkloadConditionType.ADMITTED, True, reason="Admitted",
                    message=f'Admitted by cluster "{st.winner}"', now=now,
                )
                self.runtime.event(
                    "Admitted", wl, f'Admitted by cluster "{st.winner}"'
                )
        elif wl.has_quota_reservation:
            # the worker evicted/requeued its copy: reflect reality
            # locally and wait for it to re-reserve
            self._set_pending(
                wl,
                f'reservation lost on cluster "{st.winner}"; waiting',
                now,
            )

    def _depose_winner(
        self, wl: Workload, st: DispatchState, now: float, why: str,
        strike: bool = True,
        cascade: bool = True,
    ) -> None:
        """Fence bump: the current winner is no longer trusted. The old
        epoch's copy gets an at-least-once retraction (delivered when
        the partition heals — the healed deposed winner CANNOT keep the
        gang, its token is stale everywhere), the workload re-disperses
        to the surviving clusters under the new fence.

        Gang atomicity (the JobSet/gang sync adapter): when the deposed
        workload carries a ``GANG_LABEL``, every sibling whose winner is
        the SAME deposed cluster is deposed in the same pass — their
        retractions enqueue together, so a partial gang can never stay
        reserved on a cluster the rest of the gang just left."""
        old = st.winner
        st.winner = None
        st.fence += 1
        wl.labels.pop(WINNER_LABEL, None)
        if old is not None:
            self._enqueue_retraction(st.key, old, st.fence - 1)
            if strike:
                self._strike_cluster(old, now)
        order = [
            c.name for c in self.rank_clusters(wl) if c.name != old
        ]
        if (
            not order
            and old is not None
            and old in self.clusters
            and old not in self.cordoned
        ):
            order = [old]  # last cluster standing: keep trying it
        st.clusters = order[: self.fanout] if self.fanout else order
        st.mirrored = set()
        self._journal(
            DISPATCH_RECORD,
            {"key": st.key, "fence": st.fence, "clusters": st.clusters},
        )
        self._set_pending(
            wl, f"{why}; requeued for re-dispatch (fence {st.fence})", now
        )
        self.runtime.event(
            "MultiKueueClusterLost", wl,
            f"{why}; re-dispatching under fence {st.fence}",
        )
        if cascade and old is not None:
            self._depose_gang_siblings(wl, st, old, now)

    def _depose_gang_siblings(
        self, wl: Workload, st: DispatchState, old: str, now: float
    ) -> None:
        """Retract a deposed winner's gang children atomically: every
        non-finished sibling sharing the gang label and placed on the
        same deposed cluster fence-bumps in this pass (no strike — the
        cluster was already charged once)."""
        gang = (wl.labels or {}).get(GANG_LABEL)
        if not gang:
            return
        for key in sorted(self.states):
            if key == st.key:
                continue
            sib_st = self.states[key]
            if sib_st.finished or sib_st.winner != old:
                continue
            sib = self.runtime.workloads.get(key)
            if sib is None or (sib.labels or {}).get(GANG_LABEL) != gang:
                continue
            self._depose_winner(
                sib, sib_st, now,
                f'gang "{gang}" member {st.key} deposed from "{old}"',
                strike=False, cascade=False,
            )

    def _set_pending(self, wl: Workload, message: str, now: float) -> None:
        qr = wl.conditions.get(WorkloadConditionType.QUOTA_RESERVED)
        if qr is None or qr.status or qr.message != message:
            wl.set_condition(
                WorkloadConditionType.QUOTA_RESERVED, False,
                reason="Pending", message=message, now=now,
            )
        if wl.conditions.get(WorkloadConditionType.ADMITTED) is not None:
            adm = wl.conditions[WorkloadConditionType.ADMITTED]
            if adm.status:
                wl.set_condition(
                    WorkloadConditionType.ADMITTED, False,
                    reason="NoReservation",
                    message="The workload has no reservation", now=now,
                )

    # ---- the retraction protocol ----
    def _enqueue_retraction(self, key: str, cluster: str, fence: int) -> None:
        """Ensure a delete is delivered to ``cluster`` AFTER this
        point. An in-flight (unacked) entry with the same dedup key
        absorbs the request; an ACKED entry is RE-OPENED — a copy can
        legitimately be recreated under the same fence after its first
        retraction acked (crash-recovery re-mirrors, then a rebalance
        moves the placement), and an old ack must not satisfy a new
        delete. The local-delete sweep in step() skips finished states
        so re-opening cannot live-lock the finished-state GC."""
        r = Retraction(key=key, cluster=cluster, fence=fence)
        m = getattr(self.runtime, "metrics", None)
        existing = self.retractions.get(r.dedup)
        if existing is not None and not existing.acked:
            if m is not None:
                m.report_retraction("deduped")
            return
        if existing is not None:
            existing.acked = False
            r = existing
        else:
            self.retractions[r.dedup] = r
        self._journal(
            RETRACT_ENQUEUE_RECORD,
            {"key": key, "cluster": cluster, "fence": fence},
        )
        if m is not None:
            m.report_retraction("enqueued")

    def pump_retractions(self) -> int:
        """Send every unacked retraction whose target is reachable.
        At-least-once: an unreachable target keeps the entry queued
        (and journaled) until a later pump lands it; a 404 on the
        remote — the copy already gone — counts as the ack, which makes
        redelivery after a lost ack harmless. Returns acks this pump."""
        m = getattr(self.runtime, "metrics", None)
        acked = 0
        for r in list(self.retractions.values()):
            if r.acked:
                continue
            cluster = self.clusters.get(r.cluster)
            if cluster is None:
                # the cluster left the federation: nothing to retract
                self._ack_retraction(r)
                acked += 1
                continue
            if not cluster.client.reachable():
                continue
            try:
                self._call(
                    cluster, "delete_workload", r.key,
                    fault_point="multikueue.lost_retraction",
                )
            except ClusterUnreachable:
                r.attempts += 1
                if m is not None:
                    m.report_retraction("retried")
                continue
            except RemoteRejected:
                r.attempts += 1
                if m is not None:
                    m.report_retraction("retried")
                continue
            self._ack_retraction(r)
            acked += 1
        return acked

    def _ack_retraction(self, r: Retraction) -> None:
        r.acked = True
        self._trace_span(
            "federation.retract", r.key,
            {"cluster": r.cluster, "fence": r.fence},
        )
        self._journal(
            RETRACT_DONE_RECORD,
            {"key": r.key, "cluster": r.cluster, "fence": r.fence},
        )
        m = getattr(self.runtime, "metrics", None)
        if m is not None:
            m.report_retraction("acked")
        self.runtime.events.record(
            "MultiKueueRetracted", r.key,
            f'retracted from cluster "{r.cluster}" (fence {r.fence})',
            regarding_kind="Workload",
        )

    # ---- finish / GC ----
    def _finish_state(self, st: DispatchState) -> None:
        for name in set(st.clusters) | st.mirrored | (
            {st.winner} if st.winner else set()
        ):
            self._enqueue_retraction(st.key, name, st.fence)
        st.finished = True

    def _gc_states(self) -> None:
        """Drop finished states once every retraction they spawned has
        acked — the dedup set must not grow with every workload the
        federation has ever seen."""
        for key in list(self.states):
            st = self.states[key]
            if not st.finished:
                continue
            if self._retractions_for(key, unacked_only=True):
                continue
            del self.states[key]
            for dedup in [
                d for d, r in self.retractions.items() if r.key == key
            ]:
                del self.retractions[dedup]

    def _retractions_for(self, key: str, unacked_only: bool = False):
        return [
            r for r in self.retractions.values()
            if r.key == key and (not unacked_only or not r.acked)
        ]

    # ---- cluster guard (quarantine for persistently failing remotes) ----
    def _strike_cluster(self, name: str, now: float) -> None:
        h = self.health.get(name)
        if h is None:
            return
        h.strikes += 1
        if (
            h.strikes >= self.cluster_quarantine_threshold
            and not h.quarantined(now)
        ):
            h.quarantined_until = now + self.cluster_quarantine_ttl_s
            self.runtime.events.record(
                "MultiKueueClusterQuarantined", f"cluster/{name}",
                f"worker cluster {name} quarantined from new dispatches "
                f"after {h.strikes} deposals (until "
                f"t={h.quarantined_until:.0f}); retractions still flow",
                regarding_kind="Cluster",
            )

    def _sweep_cluster_quarantine(self, now: float) -> None:
        for name, h in self.health.items():
            if h.quarantined_until is not None and now >= h.quarantined_until:
                h.quarantined_until = None
                h.strikes = 0
                self.runtime.events.record(
                    "MultiKueueClusterRecovered", f"cluster/{name}",
                    f"worker cluster {name} re-eligible for dispatch",
                    regarding_kind="Cluster",
                )

    # ---- observability ----
    def _update_gauges(self) -> None:
        m = getattr(self.runtime, "metrics", None)
        if m is None:
            return
        now = self.runtime.clock.now()
        active = sum(
            1
            for name, c in self.clusters.items()
            if c.client.active and not self.health[name].quarantined(now)
        )
        m.multikueue_clusters_active.set(active)
        m.elastic_workers_cordoned.set(
            len(self.cordoned & set(self.clusters))
        )
        for name in self.clusters:
            m.report_worker_health(name, self.worker_health.snapshot(name))
        m.hedge_rate.set(self.worker_health.hedge_rate())

    def health_report(self) -> dict:
        """The /healthz "federation" detail: degraded while any
        configured worker is lost, quarantined, or in latency
        probation (gray — slow but alive)."""
        now = self.runtime.clock.now()
        lost = sorted(
            name for name, c in self.clusters.items() if not c.client.active
        )
        quarantined = sorted(
            name for name, h in self.health.items() if h.quarantined(now)
        )
        cordoned = sorted(self.cordoned & set(self.clusters))
        probation = sorted(
            name for name in self.clusters
            if self.worker_health.state(name) == DEGRADED
        )
        pending_retractions = sum(
            1 for r in self.retractions.values() if not r.acked
        )
        return {
            "clusters": len(self.clusters),
            "active": len(self.clusters) - len(lost),
            "lost": lost,
            "quarantined": quarantined,
            # cordon is an operator intent, not a failure: visible here
            # but never flips "degraded"
            "cordoned": cordoned,
            # latency probation: slow-but-alive workers — no NEW
            # dispatches, still syncing and retracting
            "probation": probation,
            "hedgeRate": round(self.worker_health.hedge_rate(), 4),
            "pendingRetractions": pending_retractions,
            "workloads": len(self.states),
            "degraded": bool(lost or quarantined or probation),
        }

    def cluster_report(self) -> List[dict]:
        """`kueuectl clusters list` / GET federation clusters."""
        now = self.runtime.clock.now()
        out = []
        for name in sorted(self.clusters):
            c = self.clusters[name]
            h = self.health[name]
            snap = self.worker_health.snapshot(name)
            out.append(
                {
                    "name": name,
                    "active": c.client.active,
                    "lostSince": c.client.lost_since,
                    "quarantinedUntil": (
                        h.quarantined_until if h.quarantined(now) else None
                    ),
                    "cordoned": name in self.cordoned,
                    "strikes": h.strikes,
                    "dispatches": h.dispatches,
                    "wins": h.wins,
                    "failedAttempts": c.client.failed_attempts,
                    "health": snap["state"],
                    "rttP95": snap["rttP95"],
                    "rttP99": snap["rttP99"],
                    "errorRate": snap["errorRate"],
                    "rttSamples": snap["samples"],
                }
            )
        return out

    def status(self) -> dict:
        return {
            "health": self.health_report(),
            "clusters": self.cluster_report(),
            "workloads": [
                self.states[k].to_dict() for k in sorted(self.states)
            ],
            "retractions": [
                r.to_dict()
                for _, r in sorted(self.retractions.items())
                if not r.acked
            ],
        }
