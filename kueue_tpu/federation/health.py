"""Latency-aware worker health plane — gray-failure detection.

Every robustness layer before this one models workers as binary
alive/lost: a fixed transport timeout plus ``worker_lost_timeout``
means a *limping* worker that answers every call just under the
deadline is indistinguishable from a healthy one, silently dragging
every dispatch step, heartbeat sweep and global rescore down with it.
This module folds per-worker EWMA RTT, windowed p95/p99 quantiles,
error rate and heartbeat slack into a hysteresis state machine::

    healthy -> degraded (probation) -> lost

Probation is distinct from quarantine (strike-driven TTL) and cordon
(operator intent): a degraded worker receives no NEW dispatches but
keeps syncing its existing placements and acknowledging retractions —
the cheapest way off a gray worker is finishing the conversation, not
cutting it. Flap detection (state-change rate over a window) extends
the probation hold so an oscillating worker cannot re-enter the
dispatch rotation between its bad minutes.

The plane also owns the two latency-derived control signals:

- **adaptive deadlines**: per-call timeout = ``clamp(k * p99_rtt,
  floor, cap)`` instead of the historical fixed 10 s — healthy workers
  fail fast, slow-but-alive workers keep their (observed) budget;
- **hedged dispatch**: the hedge delay is the p95 RTT, and a global
  budget caps hedges at a few percent of calls.

Clock discipline: this module never reads time itself. RTT samples
arrive as floats measured by the dispatcher (whose ``perf_counter``
use carries the justified allowlist entry), and every schedule-
relevant decision takes ``now`` from the injected runtime clock.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

HEALTHY = "healthy"
DEGRADED = "degraded"
LOST = "lost"

STATES = (HEALTHY, DEGRADED, LOST)


def _quantile(sorted_samples: List[float], q: float) -> float:
    """Nearest-rank quantile over an already-sorted sample list."""
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1, int(q * len(sorted_samples)))
    return sorted_samples[idx]


class _WorkerRecord:
    """Per-worker rolling telemetry + state machine bookkeeping."""

    __slots__ = (
        "ewma_rtt",
        "rtts",
        "outcomes",
        "consecutive_errors",
        "last_contact",
        "state",
        "last_breach_at",
        "entered_at",
        "transitions",
        "_sorted_cache",
    )

    def __init__(self, window: int) -> None:
        self.ewma_rtt: Optional[float] = None
        self.rtts: Deque[float] = deque(maxlen=window)
        self.outcomes: Deque[bool] = deque(maxlen=window)
        self.consecutive_errors = 0
        self.last_contact: Optional[float] = None
        self.state = HEALTHY
        self.last_breach_at: Optional[float] = None
        self.entered_at = 0.0
        self.transitions: Deque[float] = deque(maxlen=32)
        self._sorted_cache: Optional[List[float]] = None

    def sorted_rtts(self) -> List[float]:
        if self._sorted_cache is None:
            self._sorted_cache = sorted(self.rtts)
        return self._sorted_cache

    def invalidate(self) -> None:
        self._sorted_cache = None

    def error_rate(self) -> float:
        if not self.outcomes:
            return 0.0
        return sum(1 for ok in self.outcomes if not ok) / len(self.outcomes)


class HealthPlane:
    """Federation-wide latency/health authority (one per dispatcher).

    All thresholds are constructor knobs so the chaos suites can pin
    them; the defaults are tuned for the historical 10 s fixed
    deadline the adaptive clamp replaces (``deadline_cap_s``).
    """

    def __init__(
        self,
        clock,
        *,
        window: int = 64,
        ewma_alpha: float = 0.3,
        deadline_k: float = 3.0,
        deadline_floor_s: float = 1.0,
        deadline_cap_s: float = 10.0,
        degrade_rtt_s: float = 2.0,
        degrade_error_rate: float = 0.5,
        degrade_min_samples: int = 3,
        slack_factor: float = 3.0,
        heartbeat_interval_s: float = 30.0,
        lost_error_streak: int = 8,
        probation_hold_s: float = 30.0,
        flap_window_s: float = 300.0,
        flap_threshold: int = 3,
        flap_extend_factor: float = 2.0,
        hold_cap_s: float = 600.0,
        hedge_budget: float = 0.05,
        hedge_min_samples: int = 8,
    ) -> None:
        self.clock = clock
        self.window = window
        self.ewma_alpha = ewma_alpha
        self.deadline_k = deadline_k
        self.deadline_floor_s = deadline_floor_s
        self.deadline_cap_s = deadline_cap_s
        self.degrade_rtt_s = degrade_rtt_s
        self.degrade_error_rate = degrade_error_rate
        self.degrade_min_samples = degrade_min_samples
        self.slack_factor = slack_factor
        self.heartbeat_interval_s = heartbeat_interval_s
        self.lost_error_streak = lost_error_streak
        self.probation_hold_s = probation_hold_s
        self.flap_window_s = flap_window_s
        self.flap_threshold = flap_threshold
        self.flap_extend_factor = flap_extend_factor
        self.hold_cap_s = hold_cap_s
        self.hedge_budget = hedge_budget
        self.hedge_min_samples = hedge_min_samples
        self._workers: Dict[str, _WorkerRecord] = {}
        # hedge budget accounting is fleet-wide: the budget bounds the
        # extra load hedging may put on the whole federation
        self.calls_total = 0
        self.hedges_total = 0

    # ---- ingestion ----------------------------------------------------
    def _rec(self, cluster: str) -> _WorkerRecord:
        rec = self._workers.get(cluster)
        if rec is None:
            rec = self._workers[cluster] = _WorkerRecord(self.window)
        return rec

    def observe_rtt(self, cluster: str, rtt_s: float, ok: bool = True) -> None:
        """One completed wire exchange: RTT plus its outcome."""
        rec = self._rec(cluster)
        rec.rtts.append(max(0.0, float(rtt_s)))
        rec.invalidate()
        rec.outcomes.append(bool(ok))
        if ok:
            rec.consecutive_errors = 0
            rec.last_contact = self.clock.now()
            if rec.ewma_rtt is None:
                rec.ewma_rtt = float(rtt_s)
            else:
                a = self.ewma_alpha
                rec.ewma_rtt = a * float(rtt_s) + (1.0 - a) * rec.ewma_rtt
        else:
            rec.consecutive_errors += 1
        self._advance(cluster, rec, self.clock.now())

    def observe_error(self, cluster: str) -> None:
        """A failed exchange with no meaningful RTT (refused/instant)."""
        rec = self._rec(cluster)
        rec.outcomes.append(False)
        rec.consecutive_errors += 1
        self._advance(cluster, rec, self.clock.now())

    def observe_contact(self, cluster: str, now: float) -> None:
        """Any successful exchange refreshes heartbeat slack."""
        self._rec(cluster).last_contact = now

    def forget(self, cluster: str) -> None:
        self._workers.pop(cluster, None)

    # ---- state machine ------------------------------------------------
    def _breach(self, rec: _WorkerRecord, now: float) -> bool:
        if len(rec.outcomes) >= self.degrade_min_samples:
            if rec.error_rate() >= self.degrade_error_rate:
                return True
        if len(rec.rtts) >= self.degrade_min_samples:
            if _quantile(rec.sorted_rtts(), 0.95) > self.degrade_rtt_s:
                return True
        if rec.last_contact is not None:
            slack = now - rec.last_contact
            if slack > self.slack_factor * self.heartbeat_interval_s:
                return True
        return False

    def _lost_grade(self, rec: _WorkerRecord) -> bool:
        return rec.consecutive_errors >= self.lost_error_streak

    def _hold_s(self, rec: _WorkerRecord, now: float) -> float:
        """Probation hold, extended exponentially by recent flaps."""
        flaps = sum(
            1 for t in rec.transitions if now - t <= self.flap_window_s
        )
        hold = self.probation_hold_s
        if flaps >= self.flap_threshold:
            hold *= self.flap_extend_factor ** (
                flaps - self.flap_threshold + 1
            )
        return min(hold, self.hold_cap_s)

    def _enter(self, rec: _WorkerRecord, state: str, now: float) -> None:
        if rec.state == state:
            return
        rec.state = state
        rec.entered_at = now
        rec.transitions.append(now)

    def _advance(self, cluster: str, rec: _WorkerRecord, now: float) -> None:
        breach = self._breach(rec, now)
        if breach:
            rec.last_breach_at = now
        if rec.state == HEALTHY:
            if self._lost_grade(rec):
                self._enter(rec, LOST, now)
            elif breach:
                self._enter(rec, DEGRADED, now)
        elif rec.state == DEGRADED:
            if self._lost_grade(rec):
                self._enter(rec, LOST, now)
            elif not breach:
                since_breach = now - (rec.last_breach_at or rec.entered_at)
                if since_breach >= self._hold_s(rec, now):
                    self._enter(rec, HEALTHY, now)
        else:  # LOST: recovery lands in probation, never straight healthy
            if rec.consecutive_errors == 0:
                self._enter(rec, DEGRADED, now)

    # ---- queries ------------------------------------------------------
    def state(self, cluster: str) -> str:
        rec = self._workers.get(cluster)
        if rec is None:
            return HEALTHY
        # heartbeat slack decays without traffic: re-evaluate on read
        self._advance(cluster, rec, self.clock.now())
        return rec.state

    def degraded(self, cluster: str) -> bool:
        return self.state(cluster) != HEALTHY

    def probation(self) -> List[str]:
        return sorted(
            name
            for name in self._workers
            if self.state(name) == DEGRADED
        )

    def rtt_quantile(self, cluster: str, q: float) -> float:
        rec = self._workers.get(cluster)
        if rec is None:
            return 0.0
        return _quantile(rec.sorted_rtts(), q)

    def ewma_rtt(self, cluster: str) -> float:
        rec = self._workers.get(cluster)
        if rec is None or rec.ewma_rtt is None:
            return 0.0
        return rec.ewma_rtt

    def error_rate(self, cluster: str) -> float:
        rec = self._workers.get(cluster)
        return rec.error_rate() if rec is not None else 0.0

    def deadline_s(self, cluster: str, cap_s: Optional[float] = None) -> float:
        """Adaptive per-call deadline: ``clamp(k*p99, floor, cap)``.

        With no samples yet (first contact) the full cap applies — the
        conservative choice for a worker we know nothing about.
        """
        cap = self.deadline_cap_s if cap_s is None else cap_s
        rec = self._workers.get(cluster)
        if rec is None or len(rec.rtts) < self.degrade_min_samples:
            return cap
        p99 = _quantile(rec.sorted_rtts(), 0.99)
        return min(cap, max(self.deadline_floor_s, self.deadline_k * p99))

    def hedge_delay_s(self, cluster: str) -> Optional[float]:
        """p95-RTT hedge delay, or None when hedging must not fire:
        too few samples to place the p95, or the fleet-wide budget is
        exhausted."""
        rec = self._workers.get(cluster)
        if rec is None or len(rec.rtts) < self.hedge_min_samples:
            return None
        if self.calls_total > 0 and (
            self.hedges_total >= self.hedge_budget * self.calls_total
        ):
            return None
        p95 = _quantile(rec.sorted_rtts(), 0.95)
        return max(self.deadline_floor_s * 0.1, p95)

    def record_call(self) -> None:
        self.calls_total += 1

    def record_hedge(self) -> None:
        self.hedges_total += 1

    def hedge_rate(self) -> float:
        if self.calls_total == 0:
            return 0.0
        return self.hedges_total / self.calls_total

    # ---- reporting ----------------------------------------------------
    def snapshot(self, cluster: str) -> Dict[str, object]:
        rec = self._workers.get(cluster)
        if rec is None:
            return {
                "state": HEALTHY,
                "ewmaRtt": 0.0,
                "rttP50": 0.0,
                "rttP95": 0.0,
                "rttP99": 0.0,
                "errorRate": 0.0,
                "samples": 0,
            }
        srtt = rec.sorted_rtts()
        return {
            "state": self.state(cluster),
            "ewmaRtt": rec.ewma_rtt or 0.0,
            "rttP50": _quantile(srtt, 0.50),
            "rttP95": _quantile(srtt, 0.95),
            "rttP99": _quantile(srtt, 0.99),
            "errorRate": rec.error_rate(),
            "samples": len(rec.rtts),
        }

    def fingerprint(self) -> Tuple[str, ...]:
        """Hashable health posture for the dispatcher's rank cache —
        a probation flip must invalidate cached rankings mid-step."""
        return tuple(
            f"{name}={self.state(name)}"
            for name in sorted(self._workers)
        )
