"""Global scheduler — the federation as ONE scheduling domain.

Layered on the PR-6 ``FederationDispatcher``, this closes the loop the
dispatcher leaves open: dispatch ranks clusters once and never looks
back. The global scheduler aggregates every worker's state into a
``GlobalSnapshot`` (federation/aggregate.py — in-process runtimes read
directly, remote workers read through the replica feed they already
serve), scores every (pending workload x cluster) pair in one batched
kernel launch (ops/global_kernel.py, numpy mirror in
``KERNEL_MIRRORS``), and — when the forecaster says another cluster
beats the current placement by more than the hysteresis threshold —
retracts and re-dispatches through the dispatcher's journaled
at-least-once retraction protocol, under the same per-workload fencing
epochs that already guarantee exactly-one admission across crashes and
partitions.

Safety model (chaos-tested in tests/test_global_scheduler.py):

- **Stale-fence CAS.** A rebalance decision is computed against the
  fence observed at aggregation time; by apply time a deposal/heal may
  have moved the placement. The apply compares the observed fence to
  the live one and DROPS the move on mismatch (``global.stale_fence``
  models the race) — a rebalance can only move the epoch it scored.
- **Crash mid-retraction.** The old winner's retraction is journaled
  before the new dispatch intent (``global.rebalance_retract`` fires
  between them): a crash there replays to "old winner still named,
  unacked retraction queued" — the pump deletes the stale copy, the
  sync loop deposes, and re-dispatch converges to exactly one
  admission, the PR-6 story unchanged.
- **Partitioned worker.** ``global.partition`` fires per worker read;
  an unreadable worker degrades to unscorable columns — never a
  rebalance target, never a reason to fail the pass.

Rebalancing only touches workloads that are dispatched but NOT yet
admitted: moving a running gang is preemption, which stays with the
deposal path.
"""

from __future__ import annotations

import time as _time
from typing import Dict, List, Optional

from kueue_tpu.federation.dispatcher import (
    DISPATCH_RECORD,
    WINNER_LABEL,
)
from kueue_tpu.testing import faults

__all__ = ["GlobalScheduler"]


class GlobalScheduler:
    def __init__(
        self,
        dispatcher,
        hysteresis_s: float = 60.0,
        rescore_interval_s: float = 30.0,
        use_device: bool = True,
        max_rebalances_per_pass: int = 8,
        rebalance_cooldown_s: float = 60.0,
        degraded_penalty_s: float = 120.0,
    ):
        self.disp = dispatcher
        self.runtime = dispatcher.runtime
        self.hysteresis_s = float(hysteresis_s)
        self.rescore_interval_s = float(rescore_interval_s)
        self.use_device = use_device
        self.max_rebalances_per_pass = int(max_rebalances_per_pass)
        # gray-failure coupling (PR 20): clusters the latency health
        # plane holds in probation get this many seconds added to
        # every forecast BEFORE the key pack — the kernel prefers
        # moving OFF them and never rebalances ONTO them, while the
        # penalty (not an invalid mask) keeps a fully-degraded
        # federation schedulable
        self.degraded_penalty_s = float(degraded_penalty_s)
        # per-workload churn guard: a workload that just moved is not
        # moved again until the cooldown lapses — forecast noise (or a
        # herd of movers chasing the same freed slot) must not bounce
        # a gang between clusters faster than admission can land
        self.rebalance_cooldown_s = float(rebalance_cooldown_s)
        self._last_moved: Dict[str, float] = {}
        #: worker name -> feed reader (JournalTailer or read runtime)
        self.readers: Dict[str, object] = {}
        self.last_rescore_at: Optional[float] = None
        self.last_report: Optional[dict] = None
        self.rescores = 0
        self.rebalances = 0
        self.rescore_ms_total = 0.0  # perf accounting (bench/perf)
        self.aggregate_ms_total = 0.0
        dispatcher.global_scheduler = self
        m = getattr(self.runtime, "metrics", None)
        if m is not None:
            m.global_pending_workloads.set(0)
            m.global_workers_reachable.set(0)

    # ---- worker feed readers (wire-only clusters) ----
    def attach_reader(self, name: str, reader) -> None:
        """Register a feed reader for a wire-only worker: a
        ``JournalTailer`` (polled once per rescore) or any object with
        a read-only ``ClusterRuntime`` under ``.runtime``."""
        self.readers[name] = reader

    def attach_feed_reader(
        self, name: str, url: str, token: Optional[str] = None,
        poll_timeout_s: float = 30.0,
    ):
        """Tail a remote worker's replication feed — the PR-9 replica
        machinery pointed at the worker. The tailer keeps a live
        read-only twin the aggregation forecasts against.
        ``poll_timeout_s`` caps the source's adaptive per-poll
        deadline."""
        from kueue_tpu.storage.tailer import HTTPTailSource, JournalTailer

        tailer = JournalTailer(
            HTTPTailSource(url, token=token, timeout=poll_timeout_s),
            now_fn=self.runtime.clock.now,
        )
        self.attach_reader(name, tailer)
        return tailer

    def _poll_readers(self) -> None:
        for reader in self.readers.values():
            poll = getattr(reader, "poll_once", None)
            if poll is None:
                continue
            try:
                poll()
            except Exception:  # noqa: BLE001 — a failed poll leaves the
                # previous twin serving; the worker scores stale or
                # unscorable, never breaks the pass
                continue

    def _degraded_mask(self, clusters):
        """bool[C] probation mask aligned to the snapshot's cluster
        order, from the dispatcher's latency health plane."""
        import numpy as np

        health = getattr(self.disp, "worker_health", None)
        mask = np.zeros(len(clusters), dtype=bool)
        if health is None:
            return mask, []
        probation = set(health.probation())
        for i, name in enumerate(clusters):
            mask[i] = name in probation
        return mask, sorted(probation & set(clusters))

    # ---- the loop ----
    def maybe_step(self) -> Optional[dict]:
        """Interval-gated rescore, called from every dispatcher pass."""
        now = self.runtime.clock.now()
        if (
            self.last_rescore_at is not None
            and now - self.last_rescore_at < self.rescore_interval_s
        ):
            return None
        return self.rescore()

    def rescore(self, apply: bool = True) -> dict:
        """One global pass: aggregate -> batched score -> (optionally)
        hysteresis-gated rebalances. Returns the pass report; with
        ``apply=False`` it is a pure read (the /global/standings and
        ``kueuectl pending-workloads --global`` payload)."""
        from kueue_tpu.federation.aggregate import collect_global_snapshot
        from kueue_tpu.ops.global_np import rescore_np

        now = self.runtime.clock.now()
        t_agg = _time.perf_counter()
        self._poll_readers()
        snap = collect_global_snapshot(self.disp, readers=self.readers)
        tta_ms, score, valid, current, rotation = snap.encode()
        aggregate_s = _time.perf_counter() - t_agg
        hysteresis_ms = int(round(self.hysteresis_s * 1000.0))
        degraded, degraded_names = self._degraded_mask(snap.clusters)
        penalty_ms = int(round(self.degraded_penalty_s * 1000.0))
        t0 = _time.perf_counter()
        path = "host"
        res = None
        if self.use_device and len(snap.keys) and len(snap.clusters):
            from kueue_tpu.ops.global_kernel import rescore_pairs

            try:
                res = rescore_pairs(
                    tta_ms, score, valid, current, rotation, hysteresis_ms,
                    degraded=degraded, degraded_penalty_ms=penalty_ms,
                )
                path = "device"
            except Exception:  # noqa: BLE001 — the mirror is the
                # guard-style host authority; a failed launch degrades,
                # never skips the pass
                res = None
        if res is None:
            res = rescore_np(
                tta_ms, score, valid, current, rotation, hysteresis_ms,
                degraded=degraded, degraded_penalty_ms=penalty_ms,
            )
        duration_s = _time.perf_counter() - t0

        candidates: List[tuple] = []
        rows = []
        snap_rows = snap.to_dict()["workloads"]
        for i, key in enumerate(snap.keys):
            best = int(res.best[i])
            best_name = snap.clusters[best] if best >= 0 else None
            gain_ms = int(res.gain_ms[i])
            rebalance = bool(res.rebalance[i])
            rows.append(
                {
                    "workload": key,
                    "current": snap.current.get(key),
                    "fence": snap.fences.get(key, 0),
                    "best": best_name,
                    "gainS": round(gain_ms / 1000.0, 3),
                    "rebalance": rebalance,
                    "ttaByClusterS": snap_rows[i]["ttaByClusterS"],
                }
            )
            if rebalance and best_name is not None:
                candidates.append((gain_ms, key, best_name, i))
        applied = []
        if apply:
            # biggest forecast gain first; cap per pass so one noisy
            # rescore cannot thrash the whole federation at once
            candidates.sort(key=lambda t: (-t[0], t[1]))
            for gain_ms, key, target, i in candidates[
                : self.max_rebalances_per_pass
            ]:
                moved = self._rebalance(
                    key, target, snap.fences.get(key, -1), gain_ms, now
                )
                if moved:
                    applied.append(
                        {
                            "workload": key,
                            "from": snap.current.get(key),
                            "to": target,
                            "gainS": round(gain_ms / 1000.0, 3),
                        }
                    )
            self.rescores += 1
            self.last_rescore_at = now
        reachable = sum(
            1 for v in snap.workers.values() if v.reachable
        )
        report = {
            "at": now,
            "path": path,
            "durationMs": round(duration_s * 1e3, 3),
            "aggregateMs": round(aggregate_s * 1e3, 3),
            "pending": len(snap.keys),
            "clusters": list(snap.clusters),
            "degradedClusters": degraded_names,
            "reachableWorkers": reachable,
            "rebalanceCandidates": len(candidates),
            "rebalanced": applied,
            "workers": {
                name: v.to_dict() for name, v in snap.workers.items()
            },
            "workloads": rows,
        }
        m = getattr(self.runtime, "metrics", None)
        if m is not None and apply:
            m.global_rescore_total.inc()
            m.global_rescore_seconds.observe(duration_s)
            m.global_pending_workloads.set(len(snap.keys))
            m.global_workers_reachable.set(reachable)
        if apply:
            self.rescore_ms_total += duration_s * 1e3
            self.aggregate_ms_total += aggregate_s * 1e3
            self.last_report = report
        return report

    def _target_degraded(self, target: str) -> bool:
        health = getattr(self.disp, "worker_health", None)
        if health is None:
            return False
        from kueue_tpu.federation.health import DEGRADED

        return health.state(target) == DEGRADED

    # ---- the move ----
    def _rebalance(
        self, key: str, target: str, observed_fence: int, gain_ms: int,
        now: float,
    ) -> bool:
        """Retract-and-redispatch one placement under its fencing
        epoch. Returns True when the move was applied."""
        m = getattr(self.runtime, "metrics", None)

        def skip(outcome: str) -> bool:
            if m is not None:
                m.global_rebalances_total.inc(outcome=outcome)
            return False

        st = self.disp.states.get(key)
        wl = self.runtime.workloads.get(key)
        if (
            st is None
            or wl is None
            or st.finished
            or st.fence == 0
            or wl.is_finished
            or wl.is_admitted
            or target not in self.disp.clusters
            # drain-ahead: a cordoned worker must not RECEIVE moves
            # (its own placements are being drained off it)
            or target in self.disp.cordoned
            # gray-failure probation: a worker the health plane holds
            # DEGRADED (apply-time check — it may have slipped into
            # probation since the snapshot scored) must not RECEIVE
            # moves either; its existing placements keep syncing
            or self._target_degraded(target)
            or st.winner == target
        ):
            return skip("skipped_gone")
        if st.winner is None and target in st.clusters:
            # still racing and the best cluster is already a target:
            # the first-reserving race covers it, nothing to move
            return skip("skipped_covered")
        moved_at = self._last_moved.get(key)
        if (
            moved_at is not None
            and now - moved_at < self.rebalance_cooldown_s
        ):
            return skip("skipped_cooldown")
        # CAS on the fencing epoch: the decision was computed against
        # the fence observed at aggregation; any movement since
        # (deposal, heal, concurrent rebalance) invalidates it
        observed = int(
            faults.transform("global.stale_fence", observed_fence)
        )
        if observed != st.fence:
            return skip("skipped_stale")
        old = st.winner or (st.clusters[0] if st.clusters else None)
        retract_from = sorted((set(st.clusters) | st.mirrored) - {target})
        st.winner = None
        st.fence += 1
        wl.labels.pop(WINNER_LABEL, None)
        # every old-epoch copy gets an at-least-once retraction under
        # the OLD fence — journaled before the new dispatch intent, so
        # a crash in the window below replays to "stale copies queued
        # for delete" and the PR-6 deposal path converges
        for name in retract_from:
            self.disp._enqueue_retraction(key, name, st.fence - 1)
        faults.fire("global.rebalance_retract")
        st.clusters = [target]
        st.mirrored = set()
        self.disp._journal(
            DISPATCH_RECORD,
            {"key": st.key, "fence": st.fence, "clusters": st.clusters},
        )
        self.disp._set_pending(
            wl,
            f'rebalanced from "{old}" to "{target}" '
            f"(forecast gain {gain_ms / 1000.0:.1f}s, fence {st.fence})",
            now,
        )
        self.disp._trace_span(
            "global.rescore", key,
            {
                "from": old,
                "to": target,
                "fence": st.fence,
                "gainMs": gain_ms,
            },
        )
        self.runtime.event(
            "MultiKueueRebalanced", wl,
            f'The workload was rebalanced from "{old}" to "{target}" '
            f"(forecast gain {gain_ms / 1000.0:.1f}s, fence {st.fence})",
        )
        self.rebalances += 1
        self._last_moved[key] = now
        if m is not None:
            m.global_rebalances_total.inc(outcome="applied")
        return True

    # ---- surfaces ----
    def standings(self) -> dict:
        """The /global/standings payload: a fresh READ-ONLY rescore
        (no rebalances applied) plus the last applied pass."""
        report = self.rescore(apply=False)
        report["lastApplied"] = (
            {
                "at": self.last_report["at"],
                "rebalanced": self.last_report["rebalanced"],
                "rebalanceCandidates": self.last_report[
                    "rebalanceCandidates"
                ],
            }
            if self.last_report is not None
            else None
        )
        report["rescores"] = self.rescores
        report["rebalances"] = self.rebalances
        report["hysteresisS"] = self.hysteresis_s
        report["rescoreIntervalS"] = self.rescore_interval_s
        return report

    def status(self) -> dict:
        return {
            "rescores": self.rescores,
            "rebalances": self.rebalances,
            "lastRescoreAt": self.last_rescore_at,
            "hysteresisS": self.hysteresis_s,
            "rescoreIntervalS": self.rescore_interval_s,
            "readers": sorted(self.readers),
        }
