"""Fault-tolerant MultiKueue federation — multi-cluster dispatch as a
first-class scenario.

A ``FederationDispatcher`` fronts N worker control planes (each a full
ClusterRuntime with its own journal, lease and guarded solver — or a
remote ``kueue_tpu.server`` reached over the existing HTTP surface),
mirrors every pending workload to the clusters the planner ranks best
by forecast time-to-admission, admits wherever quota clears first, and
retracts the losers through an idempotent, journaled retraction
protocol (dedup keys + at-least-once retries): a retraction lost to a
partition is retried until acknowledged, so it can never leave a gang
admitted twice.

Split-brain is fenced with per-workload epoch tokens: every mirrored
copy carries the dispatch fence in its labels, every sync-back echoes
it, and a stale token — a deposed winner healing after the workload
moved on — is refused and retracted instead of double-admitting. The
dispatcher's own crash windows are closed by the PR-4 journal: dispatch
intent, winner picks and the retraction queue are journaled WAL-style
and replayed by ``storage.recover``, so a dispatcher killed
mid-dispatch converges to the same federated admitted set.
"""

from kueue_tpu.federation.aggregate import (
    GlobalSnapshot,
    WorkerView,
    collect_global_snapshot,
)
from kueue_tpu.federation.dispatcher import (
    DISPATCH_RECORD,
    FEDERATION_RECORD_TYPES,
    FENCE_LABEL,
    GANG_LABEL,
    RETRACT_DONE_RECORD,
    RETRACT_ENQUEUE_RECORD,
    WINNER_LABEL,
    WINNER_RECORD,
    ClusterHealth,
    DispatchState,
    FederationDispatcher,
    Retraction,
)
from kueue_tpu.federation.global_scheduler import GlobalScheduler
from kueue_tpu.federation.placement import planner_placement_score

__all__ = [
    "FederationDispatcher",
    "GlobalScheduler",
    "GlobalSnapshot",
    "WorkerView",
    "collect_global_snapshot",
    "DispatchState",
    "Retraction",
    "ClusterHealth",
    "planner_placement_score",
    "FENCE_LABEL",
    "WINNER_LABEL",
    "GANG_LABEL",
    "DISPATCH_RECORD",
    "WINNER_RECORD",
    "RETRACT_ENQUEUE_RECORD",
    "RETRACT_DONE_RECORD",
    "FEDERATION_RECORD_TYPES",
]
