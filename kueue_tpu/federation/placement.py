"""Planner-backed cluster placement scoring.

Tesserae (PAPERS.md) treats cluster selection as a placement-policy
problem; here the PR-3 what-if planner is the placement brain: for each
candidate worker cluster the dispatcher asks "when would this cluster
admit the gang?" and mirrors to the best-ranked clusters first. A
cluster reachable only over the wire (no in-process runtime to
snapshot) scores None and ranks after every scored cluster — the
dispatcher still mirrors to it, it just never jumps the queue on a
forecast it cannot make.
"""

from __future__ import annotations

from typing import Optional


def planner_placement_score(cluster, wl) -> Optional[float]:
    """Forecast seconds until ``cluster`` would admit ``wl`` (0.0 =
    its quota clears on the next cycle), or None when unknowable —
    unreachable cluster, wire-only transport, or a shape the planner
    cannot represent. Lower is better."""
    transport = getattr(cluster, "transport", None)
    rt = getattr(transport, "runtime", None)
    if rt is None:
        return None
    client = getattr(cluster, "client", None)
    if client is not None and not client.active:
        return None
    from kueue_tpu.planner import forecast_time_to_admission

    try:
        return forecast_time_to_admission(rt, wl)
    except Exception:  # noqa: BLE001 — scoring is advisory; a raising
        # score must degrade to "unranked", never break the dispatch
        return None
