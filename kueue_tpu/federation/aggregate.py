"""Federation-wide state aggregation — the ``GlobalSnapshot``.

The global scheduler needs one coherent view of every worker control
plane: pending positions, per-cohort fair-share standings, flavor
capacities, and — the scoring input — a forecast time-to-admission for
every (pending workload, cluster) pair. This module collects that view
WITHOUT a new wire protocol: an in-process worker is read directly
through its runtime; a remote worker is read through the replica feed
it already serves (a ``JournalTailer`` over ``HTTPTailSource`` keeps a
live read-only twin, exactly the PR-9 read-replica machinery — the
global scheduler is just one more tailer in the fan-out tree).

The snapshot is device-encodable: ``encode()`` lays the per-pair
forecasts and policy scores out as the dense int64 ``[W, C]`` tensors
``ops/global_kernel.solve_rescore`` consumes, with the per-workload
current-winner column and crc32 rotation offsets the kernel's
tie-break key packs in. Aggregation is strictly read-only over every
runtime it touches (the planner forecast contract), and a worker that
cannot be read — partitioned, feedless, or mid-resync — degrades to
"unscorable" columns instead of failing the pass.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from kueue_tpu.admissionchecks.multikueue_transport import (
    ClusterUnreachable,
    TransportError,
)
from kueue_tpu.testing import faults

__all__ = [
    "WorkerView",
    "GlobalSnapshot",
    "collect_global_snapshot",
    "readable_runtime",
]


@dataclass
class WorkerView:
    """One worker cluster's aggregated standing."""

    name: str
    reachable: bool = False
    source: str = "none"  # inprocess | feed | none
    pending: int = 0
    admitted: int = 0
    #: per-CQ fair-share standings: clusterQueue, cohort, weightMilli,
    #: dominantShareMilli, pending
    queues: List[dict] = field(default_factory=list)
    #: per (flavor, resource) capacity totals across CQs
    capacities: List[dict] = field(default_factory=list)
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "reachable": self.reachable,
            "source": self.source,
            "pending": self.pending,
            "admitted": self.admitted,
            "queues": list(self.queues),
            "capacities": list(self.capacities),
            "error": self.error,
        }


@dataclass
class GlobalSnapshot:
    """The federation at one instant, scored rows ready to encode.

    Row order is ``keys`` (sorted workload keys); column order is
    ``clusters`` (sorted worker names). ``tta_ms``/``score``/``valid``
    are the kernel tensors; ``fences`` carries the dispatch fence each
    row was OBSERVED at — the rebalancer's compare-and-swap token (a
    fence that moved between aggregation and apply means the placement
    changed under us and the move must be dropped).
    """

    created_at: float
    clusters: List[str]
    workers: Dict[str, WorkerView]
    keys: List[str]
    fences: Dict[str, int]
    current: Dict[str, Optional[str]]
    tta_ms: np.ndarray  # int64[W, C]
    score: np.ndarray  # int64[W, C]
    valid: np.ndarray  # bool[W, C]

    def encode(self):
        """Kernel inputs: (tta_ms, score, valid, current_col, rotation)."""
        c = len(self.clusters)
        col = {name: j for j, name in enumerate(self.clusters)}
        current = np.array(
            [col.get(self.current.get(k) or "", -1) for k in self.keys],
            dtype=np.int32,
        )
        rotation = np.array(
            [
                zlib.crc32(k.encode()) % c if c else 0
                for k in self.keys
            ],
            dtype=np.int32,
        )
        return self.tta_ms, self.score, self.valid, current, rotation

    def to_dict(self) -> dict:
        rows = []
        for i, key in enumerate(self.keys):
            by_cluster = {}
            for j, name in enumerate(self.clusters):
                by_cluster[name] = (
                    round(int(self.tta_ms[i, j]) / 1000.0, 3)
                    if self.valid[i, j]
                    else None
                )
            rows.append(
                {
                    "workload": key,
                    "fence": self.fences.get(key, 0),
                    "current": self.current.get(key),
                    "ttaByClusterS": by_cluster,
                }
            )
        return {
            "createdAt": self.created_at,
            "clusters": list(self.clusters),
            "workers": {
                name: view.to_dict() for name, view in self.workers.items()
            },
            "workloads": rows,
        }


def readable_runtime(cluster, reader=None):
    """The runtime a worker can be READ through: its in-process runtime
    (InProcessTransport), or the live twin a feed reader (JournalTailer
    or plain runtime) maintains. Returns (runtime, source)."""
    rt = getattr(cluster.transport, "runtime", None)
    if rt is not None:
        return rt, "inprocess"
    if reader is None:
        return None, "none"
    rt = getattr(reader, "runtime", None)
    if rt is not None:
        return rt, "feed"
    if hasattr(reader, "workloads"):  # a bare ClusterRuntime
        return reader, "feed"
    return None, "none"


def _fill_worker_view(view: WorkerView, rt) -> None:
    """Pending positions, fair-share standings and flavor capacities
    for one readable worker runtime — all read-only."""
    from kueue_tpu.core.snapshot import take_snapshot

    view.admitted = sum(
        1 for wl in rt.workloads.values() if wl.is_admitted
    )
    snapshot = take_snapshot(rt.cache)
    total_pending = 0
    for cq_name in sorted(snapshot.cq_models):
        model = snapshot.cq_models[cq_name]
        pending = int(rt.queues.pending_workloads(cq_name))
        total_pending += pending
        view.queues.append(
            {
                "clusterQueue": cq_name,
                "cohort": model.cohort,
                "weightMilli": int(model.fair_sharing.weight_milli),
                "dominantShareMilli": int(
                    snapshot.dominant_resource_share(cq_name)
                ),
                "pending": pending,
            }
        )
    view.pending = total_pending
    # flavor capacities: nominal/usage summed over CQ rows per cell
    n_cq = len(snapshot.cq_models)
    nominal = snapshot.nominal[:n_cq].clip(min=0).sum(axis=0)
    usage = snapshot.local_usage[:n_cq].sum(axis=0)
    for j, fr in enumerate(snapshot.fr_list):
        view.capacities.append(
            {
                "flavor": fr.flavor,
                "resource": fr.resource,
                "nominal": int(nominal[j]),
                "usage": int(usage[j]),
                "available": int(max(0, nominal[j] - usage[j])),
            }
        )


def collect_global_snapshot(
    disp,
    readers: Optional[dict] = None,
    keys: Optional[List[str]] = None,
) -> GlobalSnapshot:
    """Aggregate every worker + score every (pending workload, cluster)
    pair. ``disp`` is the FederationDispatcher; ``readers`` maps worker
    name -> feed reader for wire-only clusters.

    Rows are the federation's REBALANCEABLE pending set: workloads with
    a dispatch state, not finished and not yet admitted (an admitted
    gang is running — moving it is preemption, which stays with the
    deposal path). The ``global.partition`` fault point fires once per
    worker read; a TransportError/ClusterUnreachable there degrades the
    worker to unscorable, anything armed as "crash" kills the pass.
    """
    from kueue_tpu.planner import forecast_time_to_admission

    readers = readers or {}
    now = disp.runtime.clock.now()
    clusters = sorted(disp.clusters)
    workers: Dict[str, WorkerView] = {}
    runtimes: Dict[str, object] = {}
    for name in clusters:
        cluster = disp.clusters[name]
        view = WorkerView(name=name)
        try:
            faults.fire("global.partition")
            rt, source = readable_runtime(cluster, readers.get(name))
        except (TransportError, ClusterUnreachable) as e:
            rt, source = None, "none"
            view.error = str(e) or "partitioned"
        view.source = source
        if rt is None:
            if not view.error:
                view.error = "no readable runtime (in-process or feed)"
        else:
            view.reachable = True
            runtimes[name] = rt
            try:
                _fill_worker_view(view, rt)
            except Exception as e:  # noqa: BLE001 — a half-applied feed
                # twin must degrade this worker, never break the pass
                view.reachable = False
                view.error = f"aggregation failed: {e!r}"
                runtimes.pop(name, None)
        workers[name] = view

    def _placement_of(st):
        return st.winner or (st.clusters[0] if st.clusters else None)

    def _reserving_remotely(key, st) -> bool:
        """The copy on the workload's current placement already holds a
        quota reservation: it WON the race, the winner pick just has
        not observed it yet. Rescoring it would read the copy's own
        admitted usage as congestion and retract a placement that is
        de-facto final — the oscillation the rebalanceable set must
        exclude (moving reserved work is preemption, not rebalancing)."""
        rt = runtimes.get(_placement_of(st) or "")
        if rt is None:
            return False
        rwl = rt.workloads.get(key)
        return rwl is not None and rwl.has_quota_reservation

    if keys is None:
        keys = sorted(
            key
            for key, st in disp.states.items()
            if not st.finished
            and key in disp.runtime.workloads
            and not disp.runtime.workloads[key].is_finished
            and not disp.runtime.workloads[key].is_admitted
            and not _reserving_remotely(key, st)
        )
    w, c = len(keys), len(clusters)
    tta_ms = np.zeros((w, c), dtype=np.int64)
    score = np.zeros((w, c), dtype=np.int64)
    valid = np.zeros((w, c), dtype=bool)
    policy = getattr(disp.runtime, "policy", None)
    for i, key in enumerate(keys):
        wl = disp.runtime.workloads.get(key)
        if wl is None:
            continue
        for j, name in enumerate(clusters):
            rt = runtimes.get(name)
            if rt is None:
                continue
            try:
                tta = forecast_time_to_admission(rt, wl)
            except Exception:  # noqa: BLE001 — scoring is advisory
                tta = None
            if tta is None:
                continue
            tta_ms[i, j] = int(round(float(tta) * 1000.0))
            valid[i, j] = True
            if policy is not None and not policy.is_default:
                flavor_names = sorted(
                    getattr(rt.cache, "flavors", {}) or {}
                )
                score[i, j] = int(
                    policy.candidate_score(wl, flavor_names)
                )
    return GlobalSnapshot(
        created_at=now,
        clusters=clusters,
        workers=workers,
        keys=list(keys),
        fences={
            k: disp.states[k].fence for k in keys if k in disp.states
        },
        current={
            # a reserving winner is THE placement; a still-racing
            # workload's placement is its best-ranked target cluster
            # (with --federation-fanout that is where it is queued)
            k: (
                disp.states[k].winner
                or (
                    disp.states[k].clusters[0]
                    if disp.states[k].clusters
                    else None
                )
            )
            for k in keys
            if k in disp.states
        },
        tta_ms=tta_ms,
        score=score,
        valid=valid,
    )
