"""kueuectl-equivalent CLI (cmd/kueuectl)."""
