"""python -m kueue_tpu.cli — the kueuectl equivalent.

Reference: cmd/kueuectl/app (create {cq,lq,rf}, list {cq,lq,workload,
rf}, stop/resume {workload,cq,lq}) plus cmd/importer (bulk pod import).
State lives in a JSON file (--state, default ./kueue-state.json) — the
CLI's durable store standing in for the API server; ``schedule`` loads
the state, runs admission cycles, and writes decisions back.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

from kueue_tpu import serialization as ser
from kueue_tpu.models.constants import StopPolicy, WorkloadConditionType
from kueue_tpu.models.workload import PodSet
from kueue_tpu.models import Workload
from kueue_tpu.resources import requests_from_spec


class State:
    def __init__(self, path: str):
        self.path = path
        self.is_chain_dir = os.path.isdir(path)
        if self.is_chain_dir:
            # a delta-checkpoint chain directory (--state-dir leaders):
            # readable as the merged anchor+deltas state
            from kueue_tpu.storage.checkpoint import load_state_any

            self.data = load_state_any(path) or ser.state_to_dict(
                [], [], [], []
            )
        elif os.path.exists(path):
            with open(path) as f:
                self.data = json.load(f)
        else:
            self.data = ser.state_to_dict([], [], [], [])

    def save(self) -> None:
        if self.is_chain_dir:
            # offline edits behind a delta chain would be silently
            # overwritten by the next checkpoint — refuse
            raise SystemExit(
                "error: state path is a delta-checkpoint chain "
                "directory (read-only from the CLI); apply changes "
                "through the running server"
            )
        with open(self.path, "w") as f:
            json.dump(self.data, f, indent=1, sort_keys=True)

    def find(self, section: str, name: str, namespace: str = "") -> dict:
        for obj in self.data.get(section, []):
            if obj["name"] == name and obj.get("namespace", "") == (namespace or obj.get("namespace", "")):
                return obj
        raise SystemExit(f"error: {section[:-1]} {name!r} not found")

    def upsert(self, section: str, obj: dict) -> None:
        items = self.data.setdefault(section, [])
        for i, existing in enumerate(items):
            if existing["name"] == obj["name"] and existing.get("namespace") == obj.get("namespace"):
                items[i] = obj
                return
        items.append(obj)

    def build_runtime(self):
        return ser.runtime_from_state(self.data)


def _parse_quotas(spec: str) -> Dict[str, str]:
    """cpu=10,memory=5Gi -> {"cpu": "10", "memory": "5Gi"}"""
    out: Dict[str, str] = {}
    for part in spec.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        if not v:
            raise SystemExit(f"error: invalid quota {part!r} (want resource=quantity)")
        out[k] = v
    return out


def _parse_labels(spec: str) -> Dict[str, str]:
    return _parse_quotas(spec)


# ---- create ----
def _server_client(args):
    """KueueClient from the shared --server connection flags."""
    from kueue_tpu.server import KueueClient

    return KueueClient(
        args.server,
        token=args.token,
        ca_cert=getattr(args, "ca_cert", None),
        insecure=getattr(args, "insecure", False),
    )


def _replica_note(client) -> None:
    """After a read against --server: tell the operator when the
    answer came from a read replica (and how stale it may be) — on
    stderr so piped/table output stays parseable."""
    if getattr(client, "served_by_replica", False):
        lag = client.last_replica_lag_s
        detail = f", lag {lag:.2f}s behind leader" if lag is not None else ""
        print(f"(replica{detail})", file=sys.stderr)


def _add_server_flags(parser, server_help):
    """--server plus its credential/trust companions (the kubeconfig
    server/token/certificate-authority triple for the CLI)."""
    parser.add_argument("--server", help=server_help)
    parser.add_argument(
        "--token", default=os.environ.get("KUEUE_AUTH_TOKEN") or None,
        help="bearer token for a secured server (default: $KUEUE_AUTH_TOKEN)",
    )
    parser.add_argument(
        "--ca-cert",
        default=os.environ.get("KUEUE_CA_CERT") or None,
        help="CA bundle verifying an https:// server (the ca.crt from "
        "the server's --tls-cert-dir; default: $KUEUE_CA_CERT)",
    )
    parser.add_argument(
        "--insecure", action="store_true",
        help="skip TLS verification (dev only)",
    )


def cmd_create_cq(state: State, args) -> None:
    quotas = _parse_quotas(args.nominal_quota)
    borrowing = _parse_quotas(args.borrowing_limit) if args.borrowing_limit else {}
    lending = _parse_quotas(args.lending_limit) if args.lending_limit else {}
    for label, limits in (("borrowing-limit", borrowing), ("lending-limit", lending)):
        unknown = set(limits) - set(quotas)
        if unknown:
            raise SystemExit(
                f"error: --{label} for resources without nominal quota: {sorted(unknown)}"
            )
    resources = [
        {
            "name": r,
            "nominalQuota": _canon(r, q),
            "borrowingLimit": _canon(r, borrowing[r]) if r in borrowing else None,
            "lendingLimit": _canon(r, lending[r]) if r in lending else None,
        }
        for r, q in quotas.items()
    ]
    obj = {
        "name": args.name,
        "cohort": args.cohort,
        "queueingStrategy": args.queuing_strategy,
        "namespaceSelector": {},
        "stopPolicy": "None",
        "admissionChecks": [],
        "preemption": {
            "reclaimWithinCohort": args.reclaim_within_cohort,
            "withinClusterQueue": args.preemption_within_cluster_queue,
            "borrowWithinCohort": {"policy": "Never", "maxPriorityThreshold": None},
        },
        "resourceGroups": [
            {
                "coveredResources": list(quotas),
                "flavors": [{"name": args.flavor, "resources": resources}],
            }
        ],
    }
    ser.cq_from_dict(obj)  # validate
    state.upsert("clusterQueues", obj)
    state.save()
    print(f"clusterqueue.kueue.x-k8s.io/{args.name} created")


def _canon(resource: str, qty: str) -> int:
    from kueue_tpu.resources import quantity_to_int

    return quantity_to_int(resource, qty)


def cmd_create_lq(state: State, args) -> None:
    obj = {
        "name": args.name,
        "namespace": args.namespace,
        "clusterQueue": args.clusterqueue,
        "stopPolicy": "None",
    }
    ser.lq_from_dict(obj)
    state.upsert("localQueues", obj)
    state.save()
    print(f"localqueue.kueue.x-k8s.io/{args.name} created")


def cmd_create_rf(state: State, args) -> None:
    obj = {
        "name": args.name,
        "nodeLabels": _parse_labels(args.node_labels) if args.node_labels else {},
        "nodeTaints": [],
        "tolerations": [],
        "topologyName": args.topology,
    }
    ser.flavor_from_dict(obj)
    state.upsert("resourceFlavors", obj)
    state.save()
    print(f"resourceflavor.kueue.x-k8s.io/{args.name} created")


def cmd_create_topology(state: State, args) -> None:
    obj = {
        "name": args.name,
        "levels": [lv for lv in args.levels.split(",") if lv],
    }
    ser.topology_from_dict(obj)  # validate
    state.upsert("topologies", obj)
    state.save()
    print(f"topology.kueue.x-k8s.io/{args.name} created")


def cmd_create_node(state: State, args) -> None:
    obj = {
        "name": args.name,
        "labels": _parse_labels(args.labels),
        "allocatable": _parse_quotas(args.allocatable),
        "taints": [],
        "ready": not args.not_ready,
        "nonTasUsage": {},
    }
    ser.node_from_dict(obj)  # validate (canonicalizes quantities)
    state.upsert("nodes", obj)
    state.save()
    print(f"node/{args.name} created")


def cmd_create_workload(state: State, args) -> None:
    import time

    tr = None
    required = args.topology_required
    preferred = args.topology_preferred
    if required or preferred:
        from kueue_tpu.models.workload import PodSetTopologyRequest

        tr = PodSetTopologyRequest(
            mode="Required" if required else "Preferred",
            level=required or preferred,
        )
    wl = Workload(
        namespace=args.namespace,
        name=args.name,
        queue_name=args.localqueue,
        priority=args.priority,
        creation_time=time.time(),
        pod_sets=(
            PodSet(
                name="main",
                count=args.count,
                requests=requests_from_spec(_parse_quotas(args.requests)),
                topology_request=tr,
            ),
        ),
    )
    state.upsert("workloads", ser.workload_to_dict(wl))
    state.save()
    print(f"workload.kueue.x-k8s.io/{args.name} created")


# ---- list ----
def _print_table(headers: List[str], rows: List[List[str]]) -> None:
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    print("  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip())
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())


def cmd_list_cq(state: State, args) -> None:
    rt = state.build_runtime()
    rows = []
    for c in state.data.get("clusterQueues", []):
        name = c["name"]
        pending = rt.queues.pending_workloads(name)
        admitted = rt.cache.admitted_count(name)
        rows.append([name, c.get("cohort") or "", str(pending), str(admitted)])
    _print_table(["NAME", "COHORT", "PENDING WORKLOADS", "ADMITTED WORKLOADS"], rows)


def cmd_list_lq(state: State, args) -> None:
    rows = [
        [l["namespace"], l["name"], l["clusterQueue"]]
        for l in state.data.get("localQueues", [])
        if not args.namespace or l["namespace"] == args.namespace
    ]
    _print_table(["NAMESPACE", "NAME", "CLUSTERQUEUE"], rows)


def cmd_list_rf(state: State, args) -> None:
    rows = [
        [f["name"], ",".join(f"{k}={v}" for k, v in f.get("nodeLabels", {}).items())]
        for f in state.data.get("resourceFlavors", [])
    ]
    _print_table(["NAME", "NODE LABELS"], rows)


def cmd_list_workload(state: State, args) -> None:
    rows = []
    for w in state.data.get("workloads", []):
        if args.namespace and w["namespace"] != args.namespace:
            continue
        wl = ser.workload_from_dict(w)
        status = "PENDING"
        if wl.is_finished:
            status = "FINISHED"
        elif wl.is_admitted:
            status = "ADMITTED"
        elif wl.has_quota_reservation:
            status = "QUOTARESERVED"
        elif not wl.active:
            status = "INACTIVE"
        rows.append([
            w["namespace"], w["name"], w.get("queueName", ""),
            wl.admission.cluster_queue if wl.admission else "", status,
        ])
    _print_table(
        ["NAMESPACE", "NAME", "LOCALQUEUE", "CLUSTERQUEUE", "STATUS"], rows
    )


def cmd_list_topology(state: State, args) -> None:
    rows = [
        [t["name"], ",".join(t.get("levels", []))]
        for t in state.data.get("topologies", [])
    ]
    _print_table(["NAME", "LEVELS"], rows)


def cmd_list_node(state: State, args) -> None:
    from kueue_tpu.resources import int_to_display

    rows = []
    for n in state.data.get("nodes", []):
        alloc = ",".join(
            # ints are canonical (server-exported state: cpu in milli);
            # strings are human-authored and render verbatim
            f"{r}={int_to_display(r, q) if isinstance(q, int) else q}"
            for r, q in n.get("allocatable", {}).items()
        )
        labels = ",".join(f"{k}={v}" for k, v in n.get("labels", {}).items())
        ready = "True" if n.get("ready", True) else "False"
        rows.append([n["name"], ready, alloc, labels])
    _print_table(["NAME", "READY", "ALLOCATABLE", "LABELS"], rows)


# ---- stop / resume ----
def cmd_stop(state: State, args) -> None:
    if args.kind == "workload":
        obj = state.find("workloads", args.name, args.namespace)
        obj["active"] = False
    elif args.kind == "clusterqueue":
        obj = state.find("clusterQueues", args.name)
        obj["stopPolicy"] = StopPolicy.HOLD_AND_DRAIN.value
    else:
        obj = state.find("localQueues", args.name, args.namespace)
        obj["stopPolicy"] = StopPolicy.HOLD_AND_DRAIN.value
    state.save()
    print(f"{args.kind}.kueue.x-k8s.io/{args.name} stopped")


def cmd_resume(state: State, args) -> None:
    if args.kind == "workload":
        obj = state.find("workloads", args.name, args.namespace)
        obj["active"] = True
    elif args.kind == "clusterqueue":
        obj = state.find("clusterQueues", args.name)
        obj["stopPolicy"] = StopPolicy.NONE.value
    else:
        obj = state.find("localQueues", args.name, args.namespace)
        obj["stopPolicy"] = StopPolicy.NONE.value
    state.save()
    print(f"{args.kind}.kueue.x-k8s.io/{args.name} resumed")


# ---- delete (cmd/kueuectl/app/delete) ----
_DELETE_SECTIONS = {
    "workload": ("workloads", "workloads"),
    "clusterqueue": ("clusterQueues", "clusterqueues"),
    "localqueue": ("localQueues", None),  # no server delete route
    "resourceflavor": ("resourceFlavors", "resourceflavors"),
    "node": ("nodes", "nodes"),  # TAS capacity inventory
}


def cmd_delete(state: State, args) -> None:
    section, server_section = _DELETE_SECTIONS[args.kind]
    # clusterqueue/resourceflavor are cluster-scoped: the namespace
    # default must not make State.find miss them
    namespaced = args.kind in ("workload", "localqueue")
    ns = getattr(args, "namespace", "") if namespaced else ""
    if getattr(args, "server", None):
        client = _server_client(args)
        if args.kind == "workload":
            client.delete_workload(ns, args.name)
        elif server_section is not None:
            client.delete(server_section, args.name)
        else:
            raise SystemExit(
                f"error: server delete not supported for {args.kind}"
            )
    else:
        obj = state.find(section, args.name, ns)
        state.data[section].remove(obj)
        state.save()
    if args.kind == "node":
        print(f"node/{args.name} deleted")  # Node is core/v1, no group
    else:
        print(f"{args.kind}.kueue.x-k8s.io/{args.name} deleted")


# ---- passthrough get (cmd/kueuectl/app/passthrough) ----
def cmd_get(state: State, args) -> None:
    section, server_section = _DELETE_SECTIONS[args.kind]
    # clusterqueue/resourceflavor are cluster-scoped: the namespace
    # default must not make State.find miss them
    namespaced = args.kind in ("workload", "localqueue")
    ns = getattr(args, "namespace", "") if namespaced else ""
    if getattr(args, "server", None):
        client = _server_client(args)
        if args.kind == "workload":
            obj = client.get_workload(ns, args.name)
        else:
            obj = client.get(server_section or section, args.name)
    else:
        obj = state.find(section, args.name, ns)
    json.dump(obj, sys.stdout, indent=1, sort_keys=True)
    print()


def cmd_version(state: State, args) -> None:
    from kueue_tpu import __version__

    print(f"kueuectl (kueue-tpu) {__version__}")


# ---- pending-workloads (visibility) ----
def _fmt_tta(v) -> str:
    return "-" if v is None else f"{float(v):.1f}s"


def cmd_pending_workloads(state: State, args) -> None:
    if getattr(args, "global_view", False):
        # federation-wide view: the global scheduler's read-only
        # rescore — every pending workload's current placement, the
        # forecast-best cluster, and whether the rebalancer would move
        # it (gain past hysteresis)
        if not getattr(args, "server", None):
            raise SystemExit(
                "error: `pending-workloads --global` reads a live "
                "federation manager; pass --server http://<manager>"
            )
        from kueue_tpu.server.client import ClientError

        client = _server_client(args)
        try:
            body = client.global_standings()
        except ClientError as e:
            if e.status == 404:
                raise SystemExit(
                    "error: the global scheduler is not enabled on "
                    "this server (start it with --federation-worker "
                    "NAME=URL --global-scheduler on)"
                )
            raise
        _replica_note(client)
        rows = []
        for row in body.get("workloads", []):
            tta = row.get("ttaByClusterS") or {}
            cur = row.get("current")
            best = row.get("best")
            rows.append(
                [
                    row["workload"],
                    cur or "-",
                    _fmt_tta(tta.get(cur)) if cur else "-",
                    best or "-",
                    _fmt_tta(tta.get(best)) if best else "-",
                    f"{float(row.get('gainS', 0.0)):.1f}s",
                    "yes" if row.get("rebalance") else "",
                ]
            )
        _print_table(
            ["WORKLOAD", "CURRENT", "TTA(CUR)", "BEST", "TTA(BEST)",
             "GAIN", "REBALANCE"],
            rows,
        )
        workers = body.get("workers", {})
        if workers:
            print()
            _print_table(
                ["CLUSTER", "READABLE", "SOURCE", "PENDING", "ADMITTED"],
                [
                    [
                        name,
                        "yes" if v.get("reachable") else "no",
                        v.get("source", ""),
                        str(v.get("pending", 0)),
                        str(v.get("admitted", 0)),
                    ]
                    for name, v in sorted(workers.items())
                ],
            )
        return
    if not args.clusterqueue:
        raise SystemExit(
            "error: pending-workloads needs a CLUSTERQUEUE (or "
            "--global against a federation manager)"
        )
    if getattr(args, "server", None):
        # live query against a running kueue_tpu.server (the reference's
        # kubectl plugin hitting the visibility apiserver)
        client = _server_client(args)
        summary = client.pending_workloads_cq(args.clusterqueue)
        _replica_note(client)
        rows = [
            [str(i["positionInClusterQueue"]), i["namespace"], i["name"],
             i["localQueueName"], str(i["priority"]),
             i.get("inadmissibleReason", "")]
            for i in summary["items"]
        ]
    else:
        from kueue_tpu.visibility import pending_workloads_in_cq

        rt = state.build_runtime()
        summary = pending_workloads_in_cq(
            rt.queues, args.clusterqueue, audit=rt.audit
        )
        rows = [
            [str(pw.position_in_cluster_queue), pw.namespace, pw.name,
             pw.local_queue_name, str(pw.priority), pw.inadmissible_reason]
            for pw in summary.items
        ]
    _print_table(
        ["POSITION", "NAMESPACE", "NAME", "LOCALQUEUE", "PRIORITY", "REASON"],
        rows,
    )


# ---- explain (the decision audit trail as a timeline) ----
def _render_decision_timeline(key: str, status: str, rows: List[dict]) -> None:
    """Render one workload's decision history (wire dicts, oldest
    first) the way `kubectl describe` renders conditions: one line per
    decision plus indented detail for flavors/rejections/victims."""
    print(f"Workload:      {key}")
    print(f"Status:        {status}")
    if not rows:
        print("Decisions:     <none recorded>")
        print(
            "  (the workload was never nominated — check that its "
            "LocalQueue exists and the ClusterQueue is active)"
        )
        return
    print("Decisions:")
    for d in rows:
        cycles = (
            f"cycle {d['cycle']}"
            if d.get("lastCycle", d["cycle"]) == d["cycle"]
            else f"cycles {d['cycle']}-{d['lastCycle']}"
        )
        seen = f" (seen x{d['count']})" if d.get("count", 1) > 1 else ""
        via = d.get("nominatedVia", "host")
        print(
            f"  {cycles} [{d.get('resolution', 'host')}/{via}] "
            f"{d['outcome']}: {d['reason']}{seen}"
        )
        if d.get("message"):
            print(f"      message:  {d['message']}")
        for ps_name, fmap in sorted(d.get("flavors", {}).items()):
            chosen = ", ".join(f"{r}->{f}" for r, f in sorted(fmap.items()))
            print(f"      podset {ps_name}: {chosen}")
        sc = d.get("scores")
        if sc:
            # admission-policy flavor score breakdown (kueue_tpu/policy)
            per = sc.get("perFlavor", {})
            ranked = sorted(per.items(), key=lambda t: (-t[1], t[0]))
            line = ", ".join(f"{f}={v}" for f, v in ranked)
            print(
                f"      scores [{sc.get('policy', '?')}]: {line} "
                f"(winner {sc.get('winner', '?')}, "
                f"margin {sc.get('margin', 0)})"
            )
        for ps_name, reasons in sorted(d.get("flavorReasons", {}).items()):
            for r in reasons:
                print(f"      rejected [{ps_name}]: {r}")
        pre = d.get("preemption")
        if pre:
            if pre.get("blocked"):
                print(f"      preemption blocked: {pre['blocked']}")
            for v in pre.get("victims", []):
                print(
                    f"      victim: {v['workload']} ({v['reason']})"
                )
        topo = d.get("topology")
        if topo:
            for ps_name, t in sorted(topo.items()):
                doms = "; ".join(
                    f"{'/'.join(dom['values'])} x{dom['count']}"
                    for dom in t.get("domains", [])
                )
                print(f"      topology [{ps_name}]: {doms}")


def _render_trace_summary(rows: List[dict], trace_payload: dict) -> None:
    """The explain footer (kueue_tpu/tracing): the workload's trace id
    plus per-span durations of the cycle that produced its LAST
    decision — where the time between enqueue and that decision went."""
    tid = trace_payload.get("traceId") or next(
        (d["traceId"] for d in reversed(rows) if d.get("traceId")), None
    )
    if not tid:
        return
    print(f"Trace:         {tid}")
    spans = trace_payload.get("spans", [])
    # the last decision span's cycle trace carries the durations
    cycle_tid = None
    for s in spans:
        if s.get("traceId") == tid and (s.get("attrs") or {}).get("cycleTrace"):
            cycle_tid = s["attrs"]["cycleTrace"]
    if cycle_tid is None:
        return
    cycle_spans = [s for s in spans if s.get("traceId") == cycle_tid]
    if not cycle_spans:
        return
    root = next((s for s in cycle_spans if s.get("name") == "cycle"), None)
    label = ""
    if root is not None:
        attrs = root.get("attrs") or {}
        label = (
            f" (cycle {attrs.get('cycle', '?')}, "
            f"{attrs.get('resolution', '?')})"
        )
    print(f"Trace spans{label}:")
    for s in sorted(cycle_spans, key=lambda x: x.get("start", 0.0)):
        indent = "  " if s.get("name") == "cycle" else "    "
        dur = s.get("durationMs")
        dur_str = f"{dur:.3f} ms" if dur is not None else "open"
        print(f"{indent}{s.get('name')}: {dur_str}")


def cmd_explain(state: State, args) -> None:
    """Why is this workload pending (or how was it admitted)? Renders
    the decision audit trail; --server reads a live control plane,
    otherwise the state file is loaded and scheduled in memory (no
    writes) to reproduce the decisions."""
    ns, name = args.namespace, args.name
    key = f"{ns}/{name}"
    trace_payload: dict = {}
    if getattr(args, "server", None):
        client = _server_client(args)
        wl_dict = client.get_workload(ns, name)
        wl = ser.workload_from_dict(wl_dict)
        rows = client.workload_decisions(ns, name).get("items", [])
        from kueue_tpu.server.client import ClientError

        try:
            trace_payload = client.workload_trace(ns, name)
        except (ClientError, OSError):
            trace_payload = {}  # pre-tracing server / evicted trace
        _replica_note(client)
    else:
        rt = state.build_runtime()
        rt.run_until_idle()  # in-memory only: state file is NOT saved
        wl = rt.workloads.get(key)
        if wl is None:
            raise SystemExit(f"error: workload {key!r} not found")
        rows = [r.to_dict() for r in rt.audit.for_workload(key)]
        from kueue_tpu.tracing import workload_trace_payload

        trace_payload = workload_trace_payload(rt, key)
    status = "PENDING"
    if wl.is_finished:
        status = "FINISHED"
    elif wl.is_admitted:
        status = "ADMITTED"
    elif wl.has_quota_reservation:
        status = "QUOTARESERVED"
    elif not wl.active:
        status = "INACTIVE"
    _render_decision_timeline(key, status, rows)
    _render_trace_summary(rows, trace_payload)
    # MultiKueue federation: the dispatcher stamps the winning worker
    # cluster into the local workload's labels
    from kueue_tpu.federation import WINNER_LABEL

    winner = (wl.labels or {}).get(WINNER_LABEL)
    if winner:
        print(f'Winning cluster: "{winner}" (MultiKueue federation)')


def cmd_trace(state: State, args) -> None:
    """`kueuectl trace <wl> [-o trace.json]` — the workload's full
    distributed trace: lifecycle spans plus every cycle span tree its
    decisions reference, as a text tree or (with -o) Chrome
    trace-event JSON loadable in Perfetto / chrome://tracing.
    --server reads a live control plane (leader OR replica — replicas
    mirror the leader's spans off the journal feed); otherwise the
    state file is scheduled in memory and ITS trace is rendered."""
    ns, name = args.namespace, args.name
    key = f"{ns}/{name}"
    if getattr(args, "server", None):
        client = _server_client(args)
        payload = client.workload_trace(ns, name)
        _replica_note(client)
    else:
        rt = state.build_runtime()
        rt.run_until_idle()  # in-memory only
        if key not in rt.workloads:
            raise SystemExit(f"error: workload {key!r} not found")
        from kueue_tpu.tracing import workload_trace_payload

        payload = workload_trace_payload(rt, key)
    spans = payload.get("spans", [])
    if not spans:
        print(f"Workload:      {key}")
        print("Trace:         <none recorded>")
        print(
            "  (traces are kept in a bounded in-memory store; an old "
            "workload's trace may have been evicted)"
        )
        return
    if getattr(args, "output", None):
        from kueue_tpu.tracing import to_chrome_trace

        with open(args.output, "w") as f:
            json.dump(to_chrome_trace(spans), f, indent=1)
        print(
            f"wrote {len(spans)} spans to {args.output} "
            "(Chrome trace-event JSON; open in Perfetto or "
            "chrome://tracing)"
        )
        return
    print(f"Workload:      {key}")
    print(f"Trace:         {payload.get('traceId')}")
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(s.get("traceId", ""), []).append(s)
    # lifecycle trace first, referenced cycle traces after
    ordered = sorted(
        by_trace.items(),
        key=lambda kv: (kv[0] != payload.get("traceId"), kv[0]),
    )
    for tid, group in ordered:
        kind = "lifecycle" if tid == payload.get("traceId") else "cycle"
        print(f"  [{kind}] {tid}")
        roots = {s["spanId"] for s in group if not s.get("parentId")}
        for s in sorted(group, key=lambda x: (x.get("start", 0.0))):
            indent = "    " if s["spanId"] in roots else "      "
            dur = s.get("durationMs")
            dur_str = f"{dur:.3f} ms" if dur is not None else "open"
            attrs = s.get("attrs") or {}
            extra = ""
            if "outcome" in attrs:
                extra = f" [{attrs['outcome']}/{attrs.get('reason', '')}]"
            elif "event" in attrs:
                extra = f" [{attrs['event']}]"
            print(f"{indent}{s.get('name')}: {dur_str}{extra}")


def cmd_clusters(state: State, args) -> None:
    """`kueuectl clusters list|add|cordon|uncordon|drain|remove` — the
    federation worker-cluster roster and dynamic membership: list shows
    connectivity/quarantine/cordon state, add joins a worker at
    runtime, cordon stops new dispatches, drain moves every placement
    off under the fencing protocol, remove drains then drops the
    worker. Reads/mutates a live federation manager (--server)."""
    if not getattr(args, "server", None):
        raise SystemExit(
            f"error: `kueuectl clusters {args.action}` needs a live "
            "federation manager; pass --server http://<manager>"
        )
    from kueue_tpu.server.client import ClientError

    client = _server_client(args)
    try:
        if args.action == "list":
            items = client.federation_clusters().get("items", [])
        else:
            if not args.name:
                raise SystemExit(
                    f"error: `kueuectl clusters {args.action}` needs a "
                    "worker cluster NAME"
                )
            if args.action == "add":
                if not args.url:
                    raise SystemExit(
                        "error: `kueuectl clusters add NAME --url URL` "
                        "— the worker control plane's URL is required"
                    )
                out = client.federation_add_worker(
                    args.name, args.url, token=args.worker_token
                )
                print(f"joined worker cluster {out.get('joined', args.name)}")
                return
            if args.action == "cordon":
                client.federation_cordon(args.name)
                print(
                    f"worker cluster {args.name} cordoned "
                    "(no new dispatches; existing placements stay)"
                )
                return
            if args.action == "uncordon":
                client.federation_uncordon(args.name)
                print(f"worker cluster {args.name} uncordoned")
                return
            if args.action == "drain":
                out = client.federation_drain(args.name)
                print(
                    f"worker cluster {args.name} drained: "
                    f"{out.get('deposed', 0)} placement(s) deposed and "
                    "re-dispatching onto surviving capacity"
                )
                return
            out = client.federation_remove_worker(args.name)
            print(f"worker cluster {out.get('removed', args.name)} removed")
            return
    except ClientError as e:
        if e.status == 404:
            raise SystemExit(f"error: {e}")
        raise
    rows = []
    for c in items:
        status = "Active" if c.get("active") else "Lost"
        if c.get("quarantinedUntil") is not None:
            status = "Quarantined"
        if c.get("cordoned"):
            status += ",Cordoned"
        rtt_p95 = c.get("rttP95") if c.get("rttSamples") else None
        rows.append(
            [
                c.get("name", ""),
                status,
                # latency health (gray-failure plane): healthy worker
                # vs limping worker vs lost wire, plus the windowed
                # p95 RTT its adaptive deadlines derive from
                c.get("health", "healthy"),
                "-" if rtt_p95 is None else f"{rtt_p95 * 1000.0:.0f}ms",
                str(c.get("wins", 0)),
                str(c.get("dispatches", 0)),
                str(c.get("strikes", 0)),
                (
                    "-"
                    if c.get("lostSince") is None
                    else f"t={c['lostSince']:.0f}"
                ),
            ]
        )
    _print_table(
        [
            "NAME", "STATUS", "HEALTH", "RTT-P95", "WINS", "DISPATCHES",
            "STRIKES", "LOST-SINCE",
        ],
        rows,
    )


def cmd_capacity(state: State, args) -> None:
    """`kueuectl capacity` — elastic capacity plane standings: what the
    provider has granted per flavor/resource, the journaled grant
    requests, in-flight asks, and the last chooser decision."""
    if not getattr(args, "server", None):
        raise SystemExit(
            "error: `kueuectl capacity` reads a live control plane; "
            "pass --server http://<leader>"
        )
    from kueue_tpu.server.client import ClientError

    client = _server_client(args)
    try:
        out = client.capacity()
    except ClientError as e:
        if e.status == 404:
            raise SystemExit(
                "error: the elastic capacity plane is not enabled on "
                "this server (start it with --elastic on)"
            )
        raise
    granted = out.get("granted") or {}
    rows = [
        [flavor, resource, str(amount)]
        for flavor in sorted(granted)
        for resource, amount in sorted(granted[flavor].items())
    ]
    _print_table(["FLAVOR", "RESOURCE", "GRANTED"], rows or [["-", "-", "0"]])
    print(
        f"provider: {out.get('provider', '?')}  "
        f"applied grants: {len(out.get('appliedRequests') or [])}  "
        f"in-flight: {len(out.get('inFlight') or [])}  "
        f"chooser launches: {out.get('chooserLaunches', 0)}"
    )
    last = out.get("lastChoice")
    if last:
        scores = ", ".join(
            f"{name}={score}"
            for name, score in sorted(
                (last.get("scores") or {}).items()
            )
        )
        print(
            f"last chooser pass ({last.get('backend', '?')}, "
            f"{last.get('launches', 0)} launch(es)): "
            f"chose {last.get('chosen', '?')}"
            + (f" [{scores}]" if scores else "")
        )


def cmd_replicas(state: State, args) -> None:
    """`kueuectl replicas` — the read-replica roster: on a leader,
    every follower that polled the replication feed with how far
    behind it is; pointed at a replica, that replica's own tail
    status."""
    if not getattr(args, "server", None):
        raise SystemExit(
            "error: `kueuectl replicas` reads a live control plane; "
            "pass --server http://<leader-or-replica>"
        )
    client = _server_client(args)
    out = client.replicas()
    if out.get("role") == "replica":
        rows = [
            [
                s.get("id", ""),
                str(s.get("hop", 1)),
                str(s.get("appliedSeq", 0)),
                f"{s.get('lagSeconds', 0.0):.3f}s",
                "/".join(
                    f"{x:.3f}" for x in s.get("pathLagSeconds", [])
                ) or "-",
                str(s.get("resyncs", 0)),
                str(s.get("recordsApplied", 0)),
                s.get("lastError", "") or "-",
            ]
            for s in out.get("items", [])
        ]
        _print_table(
            ["ID", "HOP", "APPLIED-SEQ", "LAG", "PATH-LAG", "RESYNCS",
             "RECORDS", "LAST-ERROR"],
            rows,
        )
        print(f"(replica of {out['items'][0].get('leader', '?')})"
              if out.get("items") else "(replica)")
        children = out.get("children") or []
        if children:
            print()
            print("downstream replicas tailing this node:")
            _print_table(
                ["ID", "HOP", "APPLIED-SEQ", "BEHIND", "LAG", "LAST-POLL"],
                [
                    [
                        r.get("id", ""),
                        str(r.get("hop", "?")),
                        str(r.get("appliedSeq", 0)),
                        str(r.get("behind", 0)),
                        f"{r.get('lagSeconds', 0.0):.3f}s",
                        f"{r.get('lastSeenAgoS', 0.0):.1f}s ago",
                    ]
                    for r in children
                ],
            )
        return
    rows = [
        [
            r.get("id", ""),
            str(r.get("hop", 1)),
            str(r.get("appliedSeq", 0)),
            str(r.get("behind", 0)),
            f"{r.get('lagSeconds', 0.0):.3f}s",
            f"{r.get('lastSeenAgoS', 0.0):.1f}s ago",
        ]
        for r in out.get("items", [])
    ]
    _print_table(
        ["ID", "HOP", "APPLIED-SEQ", "BEHIND", "LAG", "LAST-POLL"], rows
    )
    print(f"leader journal head: seq {out.get('lastSeq', 0)}")


def cmd_slo(state: State, args) -> None:
    """`kueuectl slo` — admission-SLO standings: per-ClusterQueue p95
    queue-to-admission target, attainment ratio and error-budget burn
    rate (the kueue_slo_* family, rendered)."""
    if not getattr(args, "server", None):
        raise SystemExit(
            "error: `kueuectl slo` reads a live control plane; "
            "pass --server http://<leader>"
        )
    client = _server_client(args)
    out = client.slo()
    _replica_note(client)
    if not out.get("enabled"):
        print(
            "SLO tracking is not configured on this control plane "
            "(start the server with --slo-target-p95 / --slo-target)"
        )
        return
    rows = []
    for e in out.get("clusterQueues", []):
        burn = e.get("burnRate", 0.0)
        if e.get("degraded"):
            status = "BURNING"
        elif e.get("burningSinceS") is not None:
            status = "burning"
        else:
            status = "ok"
        rows.append(
            [
                e.get("clusterQueue", ""),
                f"{e.get('targetSeconds', 0.0):g}s",
                f"{e.get('attainment', 1.0) * 100:.2f}%",
                f"{out.get('objective', 0.95) * 100:g}%",
                f"{burn:.2f}x",
                str(e.get("admitted", 0)),
                status,
            ]
        )
    _print_table(
        ["CLUSTERQUEUE", "TARGET-P95", "ATTAINMENT", "OBJECTIVE",
         "BURN", "ADMITTED", "STATUS"],
        rows,
    )
    if not rows:
        print(
            "(no admissions observed yet for any targeted ClusterQueue)"
        )
    window = out.get("burnWindowSeconds", 0)
    print(
        f"burn window {window:g}s; threshold "
        f"{out.get('burnThreshold', 0):g}x sustained "
        f"{out.get('sustainSeconds', 0):g}s -> degraded"
        + ("  ** DEGRADED **" if out.get("degraded") else "")
    )


# ---- plan (the what-if capacity planner) ----
def _render_plan(report: dict, target: str) -> None:
    """Render one PlanReport (wire dict) as a ranked scenario table
    plus the recommendation line — the operator-facing half of the
    stuck-workload loop (`explain` says why; `plan` says what next)."""
    rows = []
    for s in report.get("scenarios", []):
        fc = s.get("forecast") or {}
        band = fc.get("band")
        rows.append([
            s["name"] + (" *" if s.get("baseline") else ""),
            str(len(s.get("admitted", []))),
            "+" + str(len(s.get("newlyAdmitted", []))),
            str(len(s.get("lost", []))),
            str(s.get("preemptionCandidates", 0)),
            str(s.get("borrowing", 0)),
            (
                f"{fc.get('mean', 0)}s [{band[0]}-{band[1]}]"
                if band
                else ""
            ),
        ])
    _print_table(
        ["SCENARIO", "ADMITS", "NEW", "LOST", "PREEMPT", "BORROW", "TTA FORECAST"],
        rows,
    )
    print("(* = baseline: the cluster as configured today)")
    baseline = report.get("baseline") or {}
    if target and target in (baseline.get("reasons") or {}):
        why = baseline["reasons"][target]
        print(f"Today:         {target} is pending: {why['reason']}")
    rec = report.get("recommended")
    if rec:
        scen = next(
            (s for s in report["scenarios"] if s["name"] == rec), None
        )
        newly = ", ".join(scen.get("newlyAdmitted", [])) if scen else ""
        print(f"Recommended:   {rec}")
        if scen:
            for d in scen.get("deltas", []):
                print(f"  apply:       {d}")
        if newly:
            print(f"  would admit: {newly}")
    else:
        print(
            "Recommended:   <none> — no evaluated scenario admits "
            "anything the baseline doesn't"
        )
    if report.get("unmodeled"):
        print(
            "Unmodeled (host-path-only heads, excluded from the sweep): "
            + ", ".join(report["unmodeled"])
        )


def cmd_plan(state: State, args) -> None:
    """What would it take to admit this workload (or drain this CQ's
    backlog)? --server plans against a live control plane; otherwise
    the state file is loaded and planned in memory (no writes), like
    `explain`'s offline mode."""
    target = f"{args.namespace}/{args.name}" if args.name else ""
    options: Dict[str, object] = {"includeReasons": "baseline"}
    if args.forecast:
        options["forecast"] = True
        options["runtimeHintSeconds"] = args.runtime_hint
    scenarios = None
    if args.scenarios:
        with open(args.scenarios) as f:
            scenarios = json.load(f)
    if getattr(args, "policy", ""):
        scenarios = list(scenarios or [])
        scenarios.append(
            {
                "name": f"policy {args.policy}",
                "deltas": [{"kind": "policy", "policy": args.policy}],
            }
        )
    if not target and not args.clusterqueue and not scenarios:
        raise SystemExit(
            "error: plan needs a workload name, --clusterqueue, or "
            "--scenarios"
        )
    if getattr(args, "server", None):
        client = _server_client(args)
        report = client.plan(
            scenarios=scenarios,
            workload=target or None,
            cluster_queue=args.clusterqueue or None,
            options=options,
        )
        # a replica's plan is best-effort-stale by design: its state
        # trails the leader by the tail poll interval
        _replica_note(client)
    else:
        from kueue_tpu.planner import Planner, scenario_from_dict

        rt = state.build_runtime()
        rt.run_until_idle()  # in-memory only: state file is NOT saved
        planner = Planner.for_runtime(rt)
        hint = args.runtime_hint
        report = planner.plan(
            scenarios=(
                [
                    scenario_from_dict(sd, default_name=f"scenario-{i}")
                    for i, sd in enumerate(scenarios)
                ]
                if scenarios
                else None
            ),
            target_workload=target,
            target_cq=args.clusterqueue or "",
            include_reasons="baseline",
            forecast=args.forecast,
            runtime_hint=(lambda wl: hint) if args.forecast else None,
        ).to_dict()
    _render_plan(report, target)


# ---- state (offline durability tooling: fsck + replay) ----
def cmd_state_verify(state, args) -> None:
    """Offline fsck of the durable state: checkpoint parseability,
    journal chain (CRC framing, seq monotonicity, fencing tokens),
    then a full recovery into memory and the control-plane invariant
    check. Nonzero exit on corruption — run it before trusting a
    volume after an incident."""
    from kueue_tpu.storage import recover, verify_chain

    failures: List[str] = []
    ckpt_data = None
    ckpt = args.state
    if os.path.isdir(ckpt):
        # delta-checkpoint chain directory (server --state-dir): walk
        # the anchor + delta chain file-by-file, then load it the same
        # way recovery would
        from kueue_tpu.storage import load_checkpoint_chain, verify_checkpoint_chain
        from kueue_tpu.storage.checkpoint import parse_chain_name

        info = verify_checkpoint_chain(ckpt)
        for name in info.files:
            kind, base, js = parse_chain_name(name)
            if kind == "full":
                print(f"chain {name}: anchor, journalSeq={js}: OK")
            else:
                print(f"chain {name}: delta, baseSeq={base} "
                      f"journalSeq={js}: OK")
        for name in info.orphans:
            print(f"chain {name}: ORPHAN (not linked from the newest "
                  "anchor; stale or mid-GC)")
        for err in info.errors:
            print(f"chain: {err}")
        failures.extend(info.errors)
        if info.files:
            ckpt_data, _ = load_checkpoint_chain(ckpt)
            print(
                f"checkpoint chain {ckpt}: "
                f"{'OK' if info.ok else 'BROKEN'} "
                f"({len(info.files)} files, "
                f"journalSeq={info.journal_seq} "
                f"resourceVersion={info.resource_version})"
            )
        else:
            print(f"checkpoint chain {ckpt}: empty")
    elif os.path.exists(ckpt):
        try:
            with open(ckpt) as f:
                ckpt_data = json.load(f)
            persistence = ckpt_data.get("persistence", {})
            print(
                f"checkpoint {ckpt}: OK "
                f"(workloads={len(ckpt_data.get('workloads', []))} "
                f"journalSeq={persistence.get('journalSeq', 0)} "
                f"resourceVersion={persistence.get('resourceVersion', 0)} "
                f"token={persistence.get('token')})"
            )
        except (json.JSONDecodeError, ValueError) as e:
            failures.append(f"checkpoint {ckpt}: unparsable ({e})")
            print(f"checkpoint {ckpt}: CORRUPT ({e})")
    else:
        print(f"checkpoint {ckpt}: absent")

    if args.journal:
        rep = verify_chain(args.journal)
        for seg in rep.segments:
            status = "OK"
            if seg.torn:
                status = f"TORN at byte {seg.bytes_valid} ({seg.error})"
            print(
                f"segment {os.path.basename(seg.path)}: {seg.records} "
                f"records, {seg.bytes_total} bytes, "
                f"seq {seg.first_seq}-{seg.last_seq}: {status}"
            )
        if rep.torn_tail:
            print(
                "torn tail on the final segment: benign (the expected "
                "crash shape; recovery truncates and continues)"
            )
        if rep.stale_token_records:
            print(
                f"stale-fencing-token records: {rep.stale_token_records} "
                "(a deposed leader's stray appends; replay refuses them)"
            )
        failures.extend(rep.errors)
        failures.extend(rep.seq_gaps)

    if ckpt_data is not None or args.journal:
        try:
            res = recover(
                ckpt if ckpt_data is not None else None,
                args.journal or os.path.join(os.path.dirname(ckpt) or ".",
                                             "_no_journal_"),
                strict=False, readonly=True,
            )
            print(f"recovery dry run: {res.summary()}")
            for violation in res.invariant_violations:
                failures.append(f"invariant: {violation}")
        except Exception as e:  # noqa: BLE001 — fsck reports, not crashes
            failures.append(f"recovery dry run failed: {e!r}")

    if failures:
        print("FAILED:")
        for f in failures:
            print(f"  {f}")
        raise SystemExit(2)
    print("verify: OK (invariants hold)")


def cmd_state_replay(state, args) -> None:
    """Materialize a state file from checkpoint + journal — what the
    server WOULD serve after recovery, written as a normal wire-format
    state file (stdout or -o)."""
    from kueue_tpu.storage import recover

    ckpt = args.state if os.path.exists(args.state) else None
    try:
        res = recover(ckpt, args.journal, strict=False, readonly=True)
    except (json.JSONDecodeError, ValueError) as e:
        raise SystemExit(
            f"error: checkpoint {args.state!r} is unparsable ({e}); "
            "run `kueuectl state verify` for the full report"
        )
    rt = res.runtime
    out = ser.runtime_to_state(rt)
    out["persistence"]["resourceVersion"] = res.resource_version
    text = json.dumps(out, indent=1, sort_keys=True)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
        print(
            f"replayed {res.replayed} records onto "
            f"{'checkpoint' if res.checkpoint_loaded else 'empty state'} "
            f"-> {args.output}"
        )
    else:
        print(text)
    if res.invariant_violations:
        print("WARNING: recovered state violates invariants:")
        for violation in res.invariant_violations:
            print(f"  {violation}")
        raise SystemExit(2)


# ---- events (the `kubectl get events` / `--watch` analog) ----
def cmd_events(state: State, args) -> None:
    """List the control plane's recorded events, or follow them live
    (resourceVersion long-poll — the client blocks server-side until
    something newer lands; no polling loop)."""
    if not getattr(args, "server", None):
        raise SystemExit(
            "error: events requires --server (the live event stream "
            "exists only in a running kueue_tpu.server)"
        )
    client = _server_client(args)

    def row(e: dict) -> List[str]:
        return [
            str(e.get("resourceVersion", "")),
            e.get("reason", ""),
            e.get("object", ""),
            str(e.get("count", 1)),
            e.get("message", ""),
        ]

    headers = ["RV", "REASON", "OBJECT", "COUNT", "MESSAGE"]
    if args.watch:
        _print_table(headers, [])
        try:
            for e in client.watch(
                "events", resource_version=args.resource_version
            ):
                print("  ".join(row(e)))
        except KeyboardInterrupt:
            pass
        return
    out = client.events(args.resource_version)
    _replica_note(client)
    _print_table(headers, [row(e) for e in out.get("items", [])])
    print(f"resourceVersion: {out.get('resourceVersion', 0)}")


# ---- quarantine (core/guard.py poison-workload triage) ----
def cmd_quarantine(state: State, args) -> None:
    """``kueuectl quarantine list|clear`` — inspect and release the
    poison-workload quarantine. Server mode talks to the live control
    plane (/debug/quarantine); offline mode reads/edits the state
    file's ``quarantine`` section (the checkpointed entries)."""
    if getattr(args, "server", None):
        client = _server_client(args)
        if args.action == "clear":
            out = client.quarantine_clear(args.workload or None)
            cleared = out.get("cleared", [])
            print(
                f"cleared {len(cleared)} workload(s): "
                + (", ".join(cleared) if cleared else "<none>")
            )
            return
        out = client.quarantine_list()
        solver = out.get("solver", {})
        if solver:
            print(
                f"solver path: {solver.get('path')} "
                f"(breaker {solver.get('breaker')}, "
                f"{solver.get('failovers', 0)} failovers, "
                f"{solver.get('divergences', 0)} divergences)"
            )
        _print_table(
            ["WORKLOAD", "STRIKES", "SINCE", "UNTIL", "REASON"],
            [
                [
                    q.get("key", ""),
                    str(q.get("strikes", 0)),
                    f"{q.get('since', 0):.0f}",
                    f"{q.get('until', 0):.0f}",
                    q.get("message", ""),
                ]
                for q in out.get("items", [])
            ],
        )
        return
    entries = state.data.get("quarantine", [])
    if args.action == "clear":
        keep = [
            q for q in entries
            if args.workload and q.get("key") != args.workload
        ]
        cleared = [q["key"] for q in entries if q not in keep]
        state.data["quarantine"] = keep
        state.save()
        print(
            f"cleared {len(cleared)} workload(s): "
            + (", ".join(cleared) if cleared else "<none>")
        )
        return
    _print_table(
        ["WORKLOAD", "STRIKES", "SINCE", "UNTIL", "REASON"],
        [
            [
                q.get("key", ""),
                str(q.get("strikes", 0)),
                f"{q.get('since', 0):.0f}",
                f"{q.get('until', 0):.0f}",
                q.get("message", ""),
            ]
            for q in entries
        ],
    )


# ---- schedule ----
def cmd_schedule(state: State, args) -> None:
    rt = state.build_runtime()
    if getattr(args, "platform", None):
        # explicit device selection (some images pin jax_platforms in
        # sitecustomize, so the env var alone cannot force a backend)
        import jax

        jax.config.update("jax_platforms", args.platform)
    if getattr(args, "drain", False):
        # capacity what-if: the pending backlog planned in one device
        # dispatch (core/drain) and summarized; the cycle loop below
        # then takes the authoritative decisions (identical by the
        # drain parity suites, plus it handles fallbacks). Backlog
        # collection (ClusterRuntime.drain_backlog), scope selection
        # (classify_drain_scope) and dispatch (run_drain_for_scope) are
        # the SAME code the service bulk path runs, so the plan routes
        # exactly like production.
        from kueue_tpu.core.drain import (
            classify_drain_scope,
            run_drain_for_scope,
        )
        from kueue_tpu.core.queue_manager import queue_order_timestamp
        from kueue_tpu.core.snapshot import take_snapshot

        snapshot = take_snapshot(rt.cache)
        backlog = rt.drain_backlog(snapshot)
        tas_flavors = (
            set(rt.cache.tas_cache.flavors)
            if rt.cache.tas_cache is not None
            else set()
        )
        kind, pending = classify_drain_scope(
            snapshot, backlog, tas_flavors, rt.scheduler.fair_sharing
        )
        outcome = run_drain_for_scope(
            kind, snapshot, pending, rt.cache.flavors,
            tas_cache=rt.cache.tas_cache,
            fs_strategies=getattr(
                rt.scheduler.preemptor, "fs_strategies", None
            ),
            timestamp_fn=lambda wl: queue_order_timestamp(
                wl, rt.queues._ts_policy
            ),
        )
        evicted = len(getattr(outcome, "evictions", []) or [])
        # heads the classifier dropped to the cycle loop (TAS heads in
        # a preempt/fair backlog) were never planned — say so, or the
        # counts read as if they were rejected
        excluded = len(backlog) - len(pending)
        print(
            f"drain plan ({kind}): cycles={outcome.cycles} "
            f"admitted={len(outcome.admitted)} "
            f"evicted={evicted} "
            f"parked={len(outcome.parked)} "
            f"fallback={len(outcome.fallback)} "
            f"excluded={excluded}"
        )
    for _ in range(args.cycles):
        rt.run_until_idle()
    state.data["workloads"] = [
        ser.workload_to_dict(wl) for wl in rt.workloads.values()
    ]
    state.save()
    admitted = sum(1 for wl in rt.workloads.values() if wl.is_admitted)
    pending = sum(
        rt.queues.pending_workloads(name)
        for name in rt.queues.cluster_queues
    )
    print(f"admitted={admitted} pending={pending}")


# ---- importer (cmd/importer) ----
def cmd_import(state: State, args) -> None:
    """Bulk-import running pods: each becomes an admitted workload
    charging usage (cmd/importer/pod)."""
    with open(args.file) as f:
        pods = json.load(f)
    imported = 0
    skipped = 0
    lqs = {
        (l["namespace"], l["name"]): l["clusterQueue"]
        for l in state.data.get("localQueues", [])
    }
    for pod in pods:
        queue = pod.get("labels", {}).get("kueue.x-k8s.io/queue-name", "")
        cq = lqs.get((pod["namespace"], queue))
        if cq is None:
            skipped += 1
            continue
        requests = requests_from_spec(pod.get("requests", {}))
        wl = Workload(
            namespace=pod["namespace"],
            name=f"pod-{pod['name']}",
            queue_name=queue,
            pod_sets=(PodSet(name="main", count=1, requests=requests),),
        )
        # imported pods are already running: admit directly at the
        # first flavor of the CQ (importer/pod/pod.go)
        cq_obj = ser.cq_from_dict(state.find("clusterQueues", cq))
        flavor = cq_obj.resource_groups[0].flavors[0].name
        from kueue_tpu.models.workload import Admission, PodSetAssignment

        wl.admission = Admission(
            cluster_queue=cq,
            pod_set_assignments=(
                PodSetAssignment(
                    name="main",
                    flavors={r: flavor for r in requests},
                    resource_usage=dict(requests),
                    count=1,
                ),
            ),
        )
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True, "QuotaReserved")
        wl.set_condition(WorkloadConditionType.ADMITTED, True, "Admitted")
        state.upsert("workloads", ser.workload_to_dict(wl))
        imported += 1
    state.save()
    print(f"imported={imported} skipped={skipped}")


def cmd_lint(state: State, args) -> None:
    """kueuelint — the AST-based static analysis suite
    (kueue_tpu/analysis): kernel dtype/trace safety, journal<->replay
    symmetry, clock & lock discipline, registry lints. Exit 2 on
    findings the shrink-only baseline does not cover."""
    from kueue_tpu.analysis.__main__ import main as lint_main

    argv: List[str] = []
    for rule in args.rule or []:
        argv += ["--rule", rule]
    for flag in ("update_baseline", "allow_grow", "no_baseline",
                 "list_rules", "quiet"):
        if getattr(args, flag):
            argv.append("--" + flag.replace("_", "-"))
    rc = lint_main(argv)
    if rc != 0:
        raise SystemExit(rc)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="kueuectl")
    ap.add_argument("--state", default="kueue-state.json")
    sub = ap.add_subparsers(dest="cmd", required=True)

    create = sub.add_parser("create").add_subparsers(dest="kind", required=True)
    ccq = create.add_parser("clusterqueue", aliases=["cq"])
    ccq.add_argument("name")
    ccq.add_argument("--cohort")
    ccq.add_argument("--flavor", default="default")
    ccq.add_argument("--nominal-quota", required=True, help="cpu=10,memory=5Gi")
    ccq.add_argument("--borrowing-limit")
    ccq.add_argument("--lending-limit")
    ccq.add_argument("--queuing-strategy", default="BestEffortFIFO",
                     choices=["StrictFIFO", "BestEffortFIFO"])
    ccq.add_argument("--reclaim-within-cohort", default="Never",
                     choices=["Never", "LowerPriority", "Any"])
    ccq.add_argument("--preemption-within-cluster-queue", default="Never",
                     choices=["Never", "LowerPriority", "LowerOrNewerEqualPriority"])
    ccq.set_defaults(fn=cmd_create_cq)

    clq = create.add_parser("localqueue", aliases=["lq"])
    clq.add_argument("name")
    clq.add_argument("-n", "--namespace", default="default")
    clq.add_argument("-c", "--clusterqueue", required=True)
    clq.set_defaults(fn=cmd_create_lq)

    crf = create.add_parser("resourceflavor", aliases=["rf"])
    crf.add_argument("name")
    crf.add_argument("--node-labels")
    crf.add_argument("--topology")
    crf.set_defaults(fn=cmd_create_rf)

    cto = create.add_parser("topology")
    cto.add_argument("name")
    cto.add_argument(
        "--levels", required=True,
        help="comma-separated node label keys, top level first "
        "(e.g. block,rack,kubernetes.io/hostname)",
    )
    cto.set_defaults(fn=cmd_create_topology)

    cnode = create.add_parser("node")
    cnode.add_argument("name")
    cnode.add_argument(
        "--labels", required=True,
        help="topology-level labels, k=v comma-separated",
    )
    cnode.add_argument(
        "--allocatable", required=True,
        help="capacity, resource=quantity comma-separated "
        "(e.g. cpu=8,pods=32)",
    )
    cnode.add_argument("--not-ready", action="store_true")
    cnode.set_defaults(fn=cmd_create_node)

    cwl = create.add_parser("workload", aliases=["wl"])
    cwl.add_argument("name")
    cwl.add_argument("-n", "--namespace", default="default")
    cwl.add_argument("-q", "--localqueue", required=True)
    cwl.add_argument("--count", type=int, default=1)
    cwl.add_argument("--requests", required=True, help="cpu=1,memory=1Gi")
    cwl.add_argument("--priority", type=int, default=0)
    topo_group = cwl.add_mutually_exclusive_group()
    topo_group.add_argument(
        "--topology-required",
        help="gang placement: required topology level (node label key)",
    )
    topo_group.add_argument(
        "--topology-preferred",
        help="gang placement: preferred topology level (node label key)",
    )
    cwl.set_defaults(fn=cmd_create_workload)

    lst = sub.add_parser("list").add_subparsers(dest="kind", required=True)
    lcq = lst.add_parser("clusterqueue", aliases=["cq"])
    lcq.set_defaults(fn=cmd_list_cq)
    llq = lst.add_parser("localqueue", aliases=["lq"])
    llq.add_argument("-n", "--namespace", default="")
    llq.set_defaults(fn=cmd_list_lq)
    lto = lst.add_parser("topology")
    lto.set_defaults(fn=cmd_list_topology)
    lnode = lst.add_parser("node")
    lnode.set_defaults(fn=cmd_list_node)
    lrf = lst.add_parser("resourceflavor", aliases=["rf"])
    lrf.set_defaults(fn=cmd_list_rf)
    lwl = lst.add_parser("workload", aliases=["wl"])
    lwl.add_argument("-n", "--namespace", default="")
    lwl.set_defaults(fn=cmd_list_workload)

    for verb, fn in (("stop", cmd_stop), ("resume", cmd_resume)):
        p = sub.add_parser(verb)
        p.add_argument("kind", choices=["workload", "clusterqueue", "localqueue"])
        p.add_argument("name")
        p.add_argument("-n", "--namespace", default="default")
        p.set_defaults(fn=fn)

    dele = sub.add_parser("delete")
    dele.add_argument("kind", choices=sorted(_DELETE_SECTIONS))
    dele.add_argument("name")
    dele.add_argument("-n", "--namespace", default="default")
    _add_server_flags(dele, "delete on a running kueue_tpu.server instead of --state")
    dele.set_defaults(fn=cmd_delete)

    get = sub.add_parser("get")
    get.add_argument("kind", choices=sorted(_DELETE_SECTIONS))
    get.add_argument("name")
    get.add_argument("-n", "--namespace", default="default")
    _add_server_flags(get, "read from a running kueue_tpu.server instead of --state")
    get.set_defaults(fn=cmd_get)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=cmd_version)

    lnt = sub.add_parser(
        "lint",
        help="kueuelint static analysis over the kueue_tpu package",
    )
    lnt.add_argument(
        "--rule", "-r", action="append", metavar="RULE",
        help="run only this rule (repeatable)",
    )
    lnt.add_argument("--update-baseline", action="store_true")
    lnt.add_argument("--allow-grow", action="store_true")
    lnt.add_argument("--no-baseline", action="store_true")
    lnt.add_argument("--list-rules", action="store_true")
    lnt.add_argument("-q", "--quiet", action="store_true")
    lnt.set_defaults(fn=cmd_lint)

    st = sub.add_parser(
        "state",
        help="durable-state tooling: offline fsck and journal replay",
    )
    stsub = st.add_subparsers(dest="verb", required=True)
    sv = stsub.add_parser(
        "verify",
        help="fsck checkpoint + journal chain (CRC, fencing tokens, "
        "invariants); nonzero exit on corruption",
    )
    sv.add_argument(
        "--journal",
        help="journal directory (omit to verify the checkpoint alone)",
    )
    sv.set_defaults(fn=cmd_state_verify, tolerates_corrupt_state=True)
    sr = stsub.add_parser(
        "replay",
        help="materialize a state file from checkpoint + journal "
        "(what the server would serve after recovery)",
    )
    sr.add_argument("--journal", required=True, help="journal directory")
    sr.add_argument("-o", "--output", help="write here instead of stdout")
    sr.set_defaults(fn=cmd_state_replay, tolerates_corrupt_state=True)

    ev = sub.add_parser("events")
    ev.add_argument(
        "-w", "--watch", action="store_true",
        help="follow the stream live (resourceVersion long-poll; "
        "Ctrl-C to stop)",
    )
    ev.add_argument(
        "--resource-version", type=int, default=0,
        help="only events newer than this resourceVersion",
    )
    _add_server_flags(ev, "read events from a running kueue_tpu.server")
    ev.set_defaults(fn=cmd_events)

    qr = sub.add_parser(
        "quarantine",
        help="poison-workload quarantine triage: list sidelined "
        "workloads or clear (requeue) them",
    )
    qr.add_argument("action", choices=["list", "clear"])
    qr.add_argument(
        "workload", nargs="?", default="",
        help="ns/name to clear (clear with no workload releases all)",
    )
    _add_server_flags(
        qr, "live control plane to triage (default: the --state file's "
        "checkpointed quarantine section)",
    )
    qr.set_defaults(fn=cmd_quarantine)

    pw = sub.add_parser("pending-workloads")
    pw.add_argument("clusterqueue", nargs="?", default=None)
    pw.add_argument(
        "--global", dest="global_view", action="store_true",
        help="federation-wide view (needs --server pointing at a "
        "manager running --global-scheduler on): every pending "
        "workload's per-cluster forecast, current vs best placement, "
        "and per-worker standings",
    )
    _add_server_flags(pw, "query a running kueue_tpu.server instead of --state")
    pw.set_defaults(fn=cmd_pending_workloads)

    exp = sub.add_parser(
        "explain",
        help="render a workload's admission-decision history "
        "(why pending / how admitted)",
    )
    exp.add_argument("name")
    exp.add_argument("-n", "--namespace", default="default")
    _add_server_flags(
        exp, "read the decision trail from a running kueue_tpu.server"
    )
    exp.set_defaults(fn=cmd_explain)

    tr = sub.add_parser(
        "trace",
        help="render a workload's distributed trace (lifecycle + "
        "cycle span trees); -o exports Chrome trace-event JSON for "
        "Perfetto",
    )
    tr.add_argument("name")
    tr.add_argument("-n", "--namespace", default="default")
    tr.add_argument(
        "-o", "--output",
        help="write Chrome trace-event JSON here instead of printing "
        "the span tree (load in Perfetto / chrome://tracing)",
    )
    _add_server_flags(tr, "read traces from a running kueue_tpu.server")
    tr.set_defaults(fn=cmd_trace)

    cl = sub.add_parser(
        "clusters",
        help="MultiKueue federation: worker-cluster roster "
        "(connectivity, quarantine, cordon state) and dynamic "
        "membership (add / cordon / uncordon / drain / remove)",
    )
    cl.add_argument(
        "action",
        choices=["list", "add", "cordon", "uncordon", "drain", "remove"],
    )
    cl.add_argument(
        "name", nargs="?", default="",
        help="worker cluster name (every action except list)",
    )
    cl.add_argument(
        "--url", default="",
        help="worker control plane URL (clusters add)",
    )
    cl.add_argument(
        "--worker-token", default=None,
        help="bearer token the manager presents to the new worker "
        "(clusters add)",
    )
    _add_server_flags(cl, "federation manager to query (required)")
    cl.set_defaults(fn=cmd_clusters)

    cap = sub.add_parser(
        "capacity",
        help="elastic capacity plane: provider grants per "
        "flavor/resource, journaled grant requests, in-flight asks "
        "and the last chooser decision",
    )
    _add_server_flags(cap, "control plane to query (required)")
    cap.set_defaults(fn=cmd_capacity)

    repl = sub.add_parser(
        "replicas",
        help="read-replica roster: followers tailing this leader's "
        "journal and how far behind each is (or, against a replica, "
        "its own tail status)",
    )
    _add_server_flags(repl, "leader (or replica) to query (required)")
    repl.set_defaults(fn=cmd_replicas)

    slo = sub.add_parser(
        "slo",
        help="admission-SLO standings: per-ClusterQueue p95 "
        "queue-to-admission target, attainment ratio and error-budget "
        "burn rate (kueue_slo_* rendered)",
    )
    _add_server_flags(slo, "control plane to query (required)")
    slo.set_defaults(fn=cmd_slo)

    pl = sub.add_parser(
        "plan",
        help="what-if capacity planner: which config change would "
        "admit this workload (or this ClusterQueue's backlog), and "
        "when",
    )
    pl.add_argument(
        "name", nargs="?", default="",
        help="target workload name (omit with --clusterqueue)",
    )
    pl.add_argument("-n", "--namespace", default="default")
    pl.add_argument(
        "--clusterqueue", default="",
        help="plan a quota sweep for this ClusterQueue instead of one "
        "workload",
    )
    pl.add_argument(
        "--scenarios",
        help="JSON file with explicit scenarios "
        '([{"name", "deltas": [{"kind": "quota", ...}]}])',
    )
    pl.add_argument(
        "--policy", default="",
        help="what-if an admission-policy switch (kueue_tpu/policy "
        "registry, e.g. gavel): adds a policy scenario next to the "
        "baseline — run with --forecast to compare makespan/TTA "
        "before enabling --policy on the server",
    )
    pl.add_argument(
        "--forecast", action="store_true",
        help="include the virtual-time time-to-admission forecast",
    )
    pl.add_argument(
        "--runtime-hint", type=float, default=600.0,
        help="assumed per-workload runtime seconds for the forecast "
        "(default 600)",
    )
    _add_server_flags(pl, "plan against a running kueue_tpu.server")
    pl.set_defaults(fn=cmd_plan)

    sch = sub.add_parser("schedule")
    sch.add_argument("--cycles", type=int, default=1)
    sch.add_argument(
        "--drain", action="store_true",
        help="print a bulk what-if plan (whole backlog in one device "
        "dispatch) before the cycle loop decides",
    )
    sch.add_argument(
        "--platform", choices=["cpu", "tpu"],
        help="force the JAX backend for --drain dispatches",
    )
    sch.set_defaults(fn=cmd_schedule)

    imp = sub.add_parser("import")
    imp.add_argument("--file", required=True)
    imp.set_defaults(fn=cmd_import)

    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        state = State(args.state)
    except (json.JSONDecodeError, ValueError) as e:
        if getattr(args, "tolerates_corrupt_state", False):
            # `state verify`/`state replay` must run AGAINST corruption
            # — they load (and report) the file themselves
            state = None
        else:
            raise SystemExit(
                f"error: cannot parse state file {args.state!r}: {e}"
            )
    try:
        args.fn(state, args)
    except BrokenPipeError:
        # stdout closed early (e.g. piped through `head`): exit quietly
        # the way kubectl-style tools do
        try:
            sys.stdout.close()
        except OSError:
            pass
        return 141  # 128 + SIGPIPE
    return 0


if __name__ == "__main__":
    sys.exit(main())
