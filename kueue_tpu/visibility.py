"""Visibility API — on-demand pending-workloads summaries + decisions.

Reference: apis/visibility/v1beta1 + pkg/visibility (the embedded
apiserver serving PendingWorkloadsSummary subresources on CQ/LQ at
:8082). Here the same payloads are computed straight from the
QueueManager's heap snapshots (pkg/queue/manager.go:695-731) and — when
the caller hands over the decision audit log (core/audit.py) — each
pending workload carries its latest STRUCTURED inadmissibility reason,
so "why is this pending" is answerable from the position listing alone.
``workload_decisions`` exposes the full per-workload decision history
(the ``/debug/workloads/<ns>/<name>/decisions`` payload). Servers
(HTTP, gRPC) can wrap these functions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from kueue_tpu.core.queue_manager import QueueManager


@dataclass
class PendingWorkload:
    """visibility/v1beta1 PendingWorkload, extended with the latest
    audit-trail reason (empty when no decision has been recorded yet —
    e.g. a workload queued but never popped as a head)."""

    name: str
    namespace: str
    local_queue_name: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int
    inadmissible_reason: str = ""
    message: str = ""
    last_cycle: int = 0


@dataclass
class PendingWorkloadsSummary:
    items: List[PendingWorkload] = field(default_factory=list)


def pending_workloads_in_cq(
    queues: QueueManager,
    cq_name: str,
    offset: int = 0,
    limit: int = 1000,
    audit=None,
) -> PendingWorkloadsSummary:
    """pkg/visibility/api/v1beta1/pending_workloads_cq.go:37-46.

    Positions are computed over the FULL pending set (heap + parked +
    inflight, merged in heap order) before offset/limit slicing, so a
    paginated client sees stable absolute positions."""
    pending = queues.cluster_queues.get(cq_name)
    if pending is None:
        return PendingWorkloadsSummary()
    ordered = pending.snapshot_sorted()
    lq_positions: dict = {}
    items: List[PendingWorkload] = []
    for pos, wl in enumerate(ordered):
        lq_key = f"{wl.namespace}/{wl.queue_name}"
        lq_pos = lq_positions.get(lq_key, 0)
        lq_positions[lq_key] = lq_pos + 1
        if pos < offset or len(items) >= limit:
            continue
        reason = message = ""
        last_cycle = 0
        if audit is not None:
            latest = audit.latest(wl.key)
            if latest is not None:
                reason = latest.reason.value
                message = latest.message
                last_cycle = latest.last_cycle
        items.append(
            PendingWorkload(
                name=wl.name,
                namespace=wl.namespace,
                local_queue_name=wl.queue_name,
                priority=queues._priority(wl),
                position_in_cluster_queue=pos,
                position_in_local_queue=lq_pos,
                inadmissible_reason=reason,
                message=message,
                last_cycle=last_cycle,
            )
        )
    return PendingWorkloadsSummary(items=items)


def pending_workloads_in_lq(
    queues: QueueManager, namespace: str, lq_name: str,
    offset: int = 0, limit: int = 1000,
    audit=None,
) -> PendingWorkloadsSummary:
    """LQ variant: the CQ summary filtered to one LocalQueue, with LQ
    positions recomputed."""
    lq = queues.local_queues.get(f"{namespace}/{lq_name}")
    if lq is None:
        return PendingWorkloadsSummary()
    cq_summary = pending_workloads_in_cq(
        queues, lq.cluster_queue, offset=0, limit=1 << 30, audit=audit
    )
    items = [
        pw for pw in cq_summary.items
        if pw.namespace == namespace and pw.local_queue_name == lq_name
    ]
    return PendingWorkloadsSummary(items=items[offset : offset + limit])


def workload_decisions(audit, key: str) -> List[dict]:
    """The full decision history of one workload as wire dicts, oldest
    first — the /debug/workloads/<ns>/<name>/decisions payload and the
    data `kueuectl explain` renders."""
    if audit is None:
        return []
    return [rec.to_dict() for rec in audit.for_workload(key)]


def pending_position(
    queues: QueueManager, cq_name: str, key: str, audit=None
) -> Optional[PendingWorkload]:
    """One workload's pending entry (position + structured reason), or
    None when it is not pending in the ClusterQueue."""
    for pw in pending_workloads_in_cq(queues, cq_name, audit=audit).items:
        if f"{pw.namespace}/{pw.name}" == key:
            return pw
    return None
