"""Visibility API — on-demand pending-workloads summaries.

Reference: apis/visibility/v1beta1 + pkg/visibility (the embedded
apiserver serving PendingWorkloadsSummary subresources on CQ/LQ at
:8082). Here the same payloads are computed straight from the
QueueManager's heap snapshots (pkg/queue/manager.go:695-731); servers
(HTTP, gRPC) can wrap these functions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from kueue_tpu.core.queue_manager import QueueManager


@dataclass
class PendingWorkload:
    """visibility/v1beta1 PendingWorkload."""

    name: str
    namespace: str
    local_queue_name: str
    priority: int
    position_in_cluster_queue: int
    position_in_local_queue: int


@dataclass
class PendingWorkloadsSummary:
    items: List[PendingWorkload] = field(default_factory=list)


def pending_workloads_in_cq(
    queues: QueueManager, cq_name: str, offset: int = 0, limit: int = 1000
) -> PendingWorkloadsSummary:
    """pkg/visibility/api/v1beta1/pending_workloads_cq.go:37-46."""
    pending = queues.cluster_queues.get(cq_name)
    if pending is None:
        return PendingWorkloadsSummary()
    ordered = pending.snapshot_sorted()
    lq_positions: dict = {}
    items: List[PendingWorkload] = []
    for pos, wl in enumerate(ordered):
        lq_key = f"{wl.namespace}/{wl.queue_name}"
        lq_pos = lq_positions.get(lq_key, 0)
        lq_positions[lq_key] = lq_pos + 1
        if pos < offset or len(items) >= limit:
            continue
        items.append(
            PendingWorkload(
                name=wl.name,
                namespace=wl.namespace,
                local_queue_name=wl.queue_name,
                priority=queues._priority(wl),
                position_in_cluster_queue=pos,
                position_in_local_queue=lq_pos,
            )
        )
    return PendingWorkloadsSummary(items=items)


def pending_workloads_in_lq(
    queues: QueueManager, namespace: str, lq_name: str,
    offset: int = 0, limit: int = 1000,
) -> PendingWorkloadsSummary:
    """LQ variant: the CQ summary filtered to one LocalQueue, with LQ
    positions recomputed."""
    lq = queues.local_queues.get(f"{namespace}/{lq_name}")
    if lq is None:
        return PendingWorkloadsSummary()
    cq_summary = pending_workloads_in_cq(
        queues, lq.cluster_queue, offset=0, limit=1 << 30
    )
    items = [
        pw for pw in cq_summary.items
        if pw.namespace == namespace and pw.local_queue_name == lq_name
    ]
    return PendingWorkloadsSummary(items=items[offset : offset + limit])
