"""Defaulting + validation rules for the core API objects.

Behavioral port of pkg/webhooks/workload_webhook.go:43-310,
clusterqueue_webhook.go:97-235, resourceflavor_webhook.go:88-120,
cohort_webhook.go:69, and the CRD CEL markers
(workload_types.go:27,36-37,261,637-641; clusterqueue_types.go:49,
166,423; localqueue_types.go:28; resourceflavor taint/toleration
rules at resourceflavor_types.go / workload_types.go:443-448).

Everything operates on the wire-format dicts from serialization.py —
the framework's admission boundary — and accumulates field errors the
way field.ErrorList does, so one request reports every problem at
once.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional, Tuple

# DNS-1123: subdomain (queue names, class names) and label (podset
# names) — the kubebuilder Pattern markers on the CRDs.
_DNS1123_SUBDOMAIN = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$"
)
_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
# label keys: optional DNS-subdomain prefix / name segment
_LABEL_NAME = re.compile(r"^([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]$")
_LABEL_VALUE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")

MAX_PODSETS = 8  # workload_types.go:36 MaxItems=8
TAINT_EFFECTS = ("NoSchedule", "PreferNoSchedule", "NoExecute")
TOLERATION_OPERATORS = ("Equal", "Exists")


class ValidationError(Exception):
    """Aggregate of field errors, the field.ErrorList.ToAggregate()
    analog."""

    def __init__(self, errors: List[Tuple[str, str]]):
        self.errors = list(errors)
        super().__init__(
            "; ".join(f"{path}: {msg}" for path, msg in self.errors)
        )


class _Errs:
    def __init__(self):
        self.items: List[Tuple[str, str]] = []

    def add(self, path: str, msg: str) -> None:
        self.items.append((path, msg))

    def raise_if_any(self) -> None:
        if self.items:
            raise ValidationError(self.items)


def _check_name(errs: _Errs, path: str, value, required: bool = True) -> None:
    if not value:
        if required:
            errs.add(path, "name is required")
        return
    if not isinstance(value, str) or len(value) > 253:
        errs.add(path, "must be a string of at most 253 characters")
        return
    if not _DNS1123_SUBDOMAIN.match(value):
        errs.add(path, "must be a lowercase RFC 1123 subdomain")


def _try_canon(obj: dict, key: str, resource: str) -> None:
    """Canonicalize one quantity field in place (the resource.Quantity
    decode the reference gets from the API machinery). Unparseable
    values are left as-is for the validator to flag."""
    from kueue_tpu.serialization import _canon_qty

    value = obj.get(key)
    if value is None or isinstance(value, int):
        return
    try:
        obj[key] = _canon_qty(resource, value)
    except Exception:  # noqa: BLE001 — validator reports it with a path
        pass


def _check_quantity(errs: _Errs, path: str, value, resource: str = ""):
    """Canonical int for the value, or None after reporting a field
    error. Accepts already-canonical ints and parseable quantity
    strings (defaulters normally canonicalize first; direct validator
    callers may pass either)."""
    from kueue_tpu.serialization import _canon_qty

    if isinstance(value, int):
        return value
    if value is None:
        return None
    try:
        return _canon_qty(resource, value)
    except Exception:  # noqa: BLE001
        errs.add(path, f"invalid quantity {value!r}")
        return None


def _check_labels(errs: _Errs, path: str, labels) -> None:
    if not isinstance(labels, dict):
        errs.add(path, "must be a string map")
        return
    for k, v in labels.items():
        name = k.rsplit("/", 1)[-1]
        if len(name) > 63 or not _LABEL_NAME.match(name):
            errs.add(f"{path}[{k}]", "invalid label key")
        if len(str(v)) > 63 or not _LABEL_VALUE.match(str(v)):
            errs.add(f"{path}[{k}]", "invalid label value")


# ---------------------------------------------------------------- workload
def default_workload(obj: dict, runtime=None) -> dict:
    """workload_webhook.go:56-68 + jobframework podset-name defaulting
    + priority-from-class (utils/priority resolves at admission; here
    the spec invariant 'priority must not be nil when priorityClassName
    is set' (workload_types.go:27) is satisfied by resolving early)."""
    from kueue_tpu.features import enabled

    out = dict(obj)
    pod_sets = [dict(ps) for ps in out.get("podSets", [])]
    if len(pod_sets) == 1 and not pod_sets[0].get("name"):
        pod_sets[0]["name"] = "main"
    for ps in pod_sets:
        if not enabled("PartialAdmission"):
            ps["minCount"] = None
        requests = dict(ps.get("requests", {}))
        for rname in requests:
            _try_canon(requests, rname, rname)
        ps["requests"] = requests
    out["podSets"] = pod_sets
    out.setdefault("active", True)
    pc_name = out.get("priorityClassName")
    if pc_name and out.get("priority") is None and runtime is not None:
        pc = runtime.cache.priority_classes.get(pc_name)
        if pc is not None:
            out["priority"] = pc.value
    return out


def validate_workload(obj: dict, old: Optional[dict] = None) -> None:
    errs = _Errs()
    _check_name(errs, "metadata.name", obj.get("name"))
    _check_name(errs, "spec.queueName", obj.get("queueName"), required=False)
    _check_name(
        errs, "spec.priorityClassName", obj.get("priorityClassName"),
        required=False,
    )
    if obj.get("priorityClassName") and obj.get("priority") is None:
        # workload_types.go:27 CEL
        errs.add(
            "spec.priority",
            "priority should not be nil when priorityClassName is set",
        )
    met = obj.get("maximumExecutionTimeSeconds")
    if met is not None and met < 1:
        errs.add("spec.maximumExecutionTimeSeconds", "must be at least 1")

    pod_sets = obj.get("podSets", [])
    if not 1 <= len(pod_sets) <= MAX_PODSETS:
        # workload_types.go:36-37 MinItems=1 MaxItems=8
        errs.add(
            "spec.podSets", f"must have between 1 and {MAX_PODSETS} elements"
        )
    seen = set()
    min_count_sets = 0
    names = set()
    for i, ps in enumerate(pod_sets):
        path = f"spec.podSets[{i}]"
        name = ps.get("name", "")
        names.add(name)
        if not name or not _DNS1123_LABEL.match(name) or len(name) > 63:
            errs.add(f"{path}.name", "must be a lowercase RFC 1123 label")
        if name in seen:
            errs.add(f"{path}.name", f"duplicate podSet name {name!r}")
        seen.add(name)
        count = ps.get("count", 0)
        if count < 1:
            errs.add(f"{path}.count", "must be at least 1")
        mc = ps.get("minCount")
        if mc is not None:
            min_count_sets += 1
            if not 0 < mc <= count:
                # workload_types.go:261 CEL
                errs.add(
                    f"{path}.minCount",
                    "minCount should be positive and less or equal to count",
                )
        for rname, qty in ps.get("requests", {}).items():
            if rname == "pods":
                # workload_webhook.go validateContainer: reserved key
                errs.add(
                    f"{path}.requests[pods]",
                    "the key is reserved for internal kueue use",
                )
            _check_quantity(errs, f"{path}.requests[{rname}]", qty, rname)
    if min_count_sets > 1:
        # workload_webhook.go:109-111
        errs.add(
            "spec.podSets",
            f"{min_count_sets} podSets use minCount; at most one podSet "
            "can use minCount",
        )

    _validate_workload_status(errs, obj, names)
    if old is not None:
        _validate_workload_update(errs, obj, old)
    errs.raise_if_any()


def _has_quota_reservation(obj: dict) -> bool:
    return any(
        c.get("type") == "QuotaReserved" and c.get("status")
        for c in obj.get("conditions", [])
    )


def _validate_workload_status(errs: _Errs, obj: dict, podset_names) -> None:
    adm = obj.get("admission")
    if adm is not None:
        psas = adm.get("podSetAssignments", [])
        if _has_quota_reservation(obj) and len(psas) != len(
            obj.get("podSets", [])
        ):
            # workload_types.go:637-641 CEL
            errs.add(
                "status.admission.podSetAssignments",
                "must have the same number of podSets as the spec",
            )
        for i, psa in enumerate(psas):
            path = f"status.admission.podSetAssignments[{i}]"
            if psa.get("name") not in podset_names:
                errs.add(f"{path}.name", f"unknown podSet {psa.get('name')!r}")
            count = psa.get("count", 0)
            if count > 0:
                for rname, qty in psa.get("resourceUsage", {}).items():
                    qty = _check_quantity(
                        errs, f"{path}.resourceUsage[{rname}]", qty, rname
                    )
                    if qty is not None and qty % count != 0:
                        errs.add(
                            f"{path}.resourceUsage[{rname}]",
                            f"is not a multiple of {count}",
                        )
    counts = {ps.get("name"): ps.get("count", 0) for ps in obj.get("podSets", [])}
    for name, count in obj.get("reclaimablePods", {}).items():
        path = f"status.reclaimablePods[{name}]"
        if name not in counts:
            errs.add(f"{path}.name", f"unknown podSet {name!r}")
        elif count > counts[name]:
            errs.add(
                f"{path}.count", f"should be less or equal to {counts[name]}"
            )


def _norm_qty_map(m: dict) -> dict:
    from kueue_tpu.serialization import _canon_qty

    out = {}
    for r, q in (m or {}).items():
        try:
            out[r] = _canon_qty(r, q)
        except Exception:  # noqa: BLE001 — unparseable compares as-is
            out[r] = q
    return out


def _norm_podsets(pod_sets) -> tuple:
    """Semantic form of a podSet list: defaults filled, quantities
    canonical — so a re-POST of the original sparse manifest compares
    equal to the fully-serialized stored copy."""
    return tuple(
        (
            ps.get("name", ""),
            ps.get("count", 0),
            ps.get("minCount"),
            tuple(sorted(_norm_qty_map(ps.get("requests")).items())),
            tuple(sorted((ps.get("nodeSelector") or {}).items())),
            (
                (ps["topologyRequest"].get("mode"), ps["topologyRequest"].get("level"))
                if ps.get("topologyRequest")
                else None
            ),
        )
        for ps in pod_sets or []
    )


def _norm_admission(adm: Optional[dict]):
    if adm is None:
        return None
    return (
        adm.get("clusterQueue", ""),
        tuple(
            (
                psa.get("name", ""),
                tuple(sorted((psa.get("flavors") or {}).items())),
                tuple(sorted(_norm_qty_map(psa.get("resourceUsage")).items())),
                psa.get("count", 0),
                (
                    (
                        tuple(psa["topologyAssignment"].get("levels", ())),
                        tuple(
                            (tuple(d.get("values", ())), d.get("count", 0))
                            for d in psa["topologyAssignment"].get("domains", ())
                        ),
                    )
                    if psa.get("topologyAssignment")
                    else None
                ),
            )
            for psa in adm.get("podSetAssignments", ())
        ),
    )


def _validate_workload_update(errs: _Errs, obj: dict, old: dict) -> None:
    """workload_webhook.go:269-310 ValidateWorkloadUpdate. Comparisons
    are over semantic forms (defaults filled, quantities canonical),
    not raw wire dicts."""
    if _has_quota_reservation(old):
        if _norm_podsets(obj.get("podSets")) != _norm_podsets(old.get("podSets")):
            errs.add("spec.podSets", "field is immutable with quota reserved")
    if old.get("admission") is not None:
        if (obj.get("queueName") or "") != (old.get("queueName") or ""):
            # workload_types.go queueName CEL: immutable while admitted
            errs.add(
                "spec.queueName",
                "field is immutable while admission is not null",
            )
        if obj.get("admission") is not None and _norm_admission(
            obj["admission"]
        ) != _norm_admission(old["admission"]):
            # admission can be set or unset but not changed
            errs.add("status.admission", "field is immutable")
    if _has_quota_reservation(old) and _has_quota_reservation(obj):
        old_recl = old.get("reclaimablePods", {})
        for name, count in obj.get("reclaimablePods", {}).items():
            if name in old_recl and count < old_recl[name]:
                # reclaimable counts must not decrease while admitted
                errs.add(
                    f"status.reclaimablePods[{name}].count",
                    f"cannot be less than {old_recl[name]}",
                )


# ---------------------------------------------------------- cluster queue
def default_cluster_queue(obj: dict, runtime=None) -> dict:
    """clusterqueue_webhook.go:59-67 — the finalizer default has no
    analog here; queueingStrategy/stopPolicy defaults come from the
    dataclass. Quantity strings in quotas are canonicalized here (the
    resource.Quantity decode)."""
    out = dict(obj)
    groups = []
    for rg in out.get("resourceGroups", []):
        rg = dict(rg)
        flavors = []
        for fq in rg.get("flavors", []):
            fq = dict(fq)
            resources = []
            for rq in fq.get("resources", []):
                rq = dict(rq)
                rname = rq.get("name", "")
                for key in ("nominalQuota", "borrowingLimit", "lendingLimit"):
                    _try_canon(rq, key, rname)
                resources.append(rq)
            fq["resources"] = resources
            flavors.append(fq)
        rg["flavors"] = flavors
        groups.append(rg)
    if groups:
        out["resourceGroups"] = groups
    return out


def _validate_resource_groups(
    errs: _Errs, obj: dict, has_parent: bool, kind_path: str = "spec"
) -> None:
    """clusterqueue_webhook.go:139-235 validateResourceGroups."""
    seen_resources = set()
    seen_flavors = set()
    for i, rg in enumerate(obj.get("resourceGroups", [])):
        rg_path = f"{kind_path}.resourceGroups[{i}]"
        covered = rg.get("coveredResources", [])
        if not covered:
            errs.add(f"{rg_path}.coveredResources", "must not be empty")
        for j, rname in enumerate(covered):
            if rname in seen_resources:
                errs.add(
                    f"{rg_path}.coveredResources[{j}]",
                    f"duplicate resource {rname!r}",
                )
            seen_resources.add(rname)
        for j, fq in enumerate(rg.get("flavors", [])):
            f_path = f"{rg_path}.flavors[{j}]"
            fname = fq.get("name", "")
            _check_name(errs, f"{f_path}.name", fname)
            if fname in seen_flavors:
                errs.add(f"{f_path}.name", f"duplicate flavor {fname!r}")
            seen_flavors.add(fname)
            resources = fq.get("resources", [])
            listed = [r.get("name") for r in resources]
            if listed != list(covered):
                # clusterqueue_types.go:166 CEL + name-order check
                errs.add(
                    f"{f_path}.resources",
                    "must match coveredResources (same names, same order)",
                )
            for k, rq in enumerate(resources):
                r_path = f"{f_path}.resources[{k}]"
                rname = rq.get("name", "")
                nominal = _check_quantity(
                    errs, f"{r_path}.nominalQuota",
                    rq.get("nominalQuota", 0), rname,
                )
                if nominal is not None and nominal < 0:
                    errs.add(f"{r_path}.nominalQuota", "must not be negative")
                limits = {}
                for limit_name in ("borrowingLimit", "lendingLimit"):
                    raw = rq.get(limit_name)
                    if raw is None:
                        continue
                    limit = _check_quantity(
                        errs, f"{r_path}.{limit_name}", raw, rname
                    )
                    if limit is None:
                        continue
                    limits[limit_name] = limit
                    if limit < 0:
                        errs.add(f"{r_path}.{limit_name}", "must not be negative")
                    if not has_parent:
                        # clusterqueue_types.go:49 CEL + validateLimit
                        errs.add(
                            f"{r_path}.{limit_name}",
                            "must be nil when cohort is empty",
                        )
                lend = limits.get("lendingLimit")
                if nominal is not None and lend is not None and lend > nominal:
                    errs.add(
                        f"{r_path}.lendingLimit",
                        "must be less than or equal to the nominalQuota",
                    )


def validate_cluster_queue(obj: dict, old: Optional[dict] = None) -> None:
    errs = _Errs()
    _check_name(errs, "metadata.name", obj.get("name"))
    _check_name(errs, "spec.cohort", obj.get("cohort"), required=False)
    _validate_resource_groups(errs, obj, has_parent=bool(obj.get("cohort")))
    prem = obj.get("preemption", {})
    borrow = prem.get("borrowWithinCohort", {})
    if (
        prem.get("reclaimWithinCohort", "Never") == "Never"
        and borrow.get("policy", "Never") != "Never"
    ):
        # clusterqueue_types.go:423 CEL / clusterqueue_webhook.go:120-128
        errs.add(
            "spec.preemption",
            "reclaimWithinCohort=Never and borrowWithinCohort.Policy!=Never",
        )
    # borrowWithinCohort.maxPriorityThreshold is optional even for
    # LowerPriority (unlimited below-priority borrow-preempt)
    weight = obj.get("fairSharingWeight")
    if weight is not None and weight < 0:
        errs.add("spec.fairSharing.weight", "must not be negative")
    errs.raise_if_any()


# ------------------------------------------------- local queue / cohort
def validate_local_queue(obj: dict, old: Optional[dict] = None) -> None:
    errs = _Errs()
    _check_name(errs, "metadata.name", obj.get("name"))
    _check_name(errs, "metadata.namespace", obj.get("namespace"))
    _check_name(errs, "spec.clusterQueue", obj.get("clusterQueue"))
    if old is not None and obj.get("clusterQueue") != old.get("clusterQueue"):
        # localqueue_types.go:28 CEL: field is immutable
        errs.add("spec.clusterQueue", "field is immutable")
    errs.raise_if_any()


def validate_cohort(obj: dict, old: Optional[dict] = None) -> None:
    errs = _Errs()
    _check_name(errs, "metadata.name", obj.get("name"))
    _check_name(errs, "spec.parent", obj.get("parent"), required=False)
    if obj.get("parent") and obj["parent"] == obj.get("name"):
        errs.add("spec.parent", "cohort cannot be its own parent")
    if "resourceGroups" in obj:
        _validate_resource_groups(
            errs, obj, has_parent=bool(obj.get("parent"))
        )
    errs.raise_if_any()


# -------------------------------------------------------- resource flavor
def validate_resource_flavor(obj: dict, old: Optional[dict] = None) -> None:
    """resourceflavor_webhook.go:88-120 + toleration CEL rules
    (workload_types.go:443-448)."""
    errs = _Errs()
    _check_name(errs, "metadata.name", obj.get("name"))
    _check_labels(errs, "spec.nodeLabels", obj.get("nodeLabels", {}))
    for i, taint in enumerate(obj.get("nodeTaints", [])):
        path = f"spec.nodeTaints[{i}]"
        if not taint.get("key"):
            errs.add(f"{path}.key", "must not be empty")
        if taint.get("effect") not in TAINT_EFFECTS:
            errs.add(
                f"{path}.effect",
                f"supported taint effect values: {', '.join(TAINT_EFFECTS)}",
            )
    for i, tol in enumerate(obj.get("tolerations", [])):
        path = f"spec.tolerations[{i}]"
        op = tol.get("operator", "Equal")
        if op not in TOLERATION_OPERATORS:
            errs.add(
                f"{path}.operator",
                "supported toleration values: 'Equal'(default), 'Exists'",
            )
        if not tol.get("key") and op != "Exists":
            errs.add(
                f"{path}.operator",
                "operator must be Exists when 'key' is empty",
            )
        if op == "Exists" and tol.get("value"):
            errs.add(
                f"{path}.value",
                "a value must be empty when 'operator' is 'Exists'",
            )
        effect = tol.get("effect", "")
        if effect and effect not in TAINT_EFFECTS:
            errs.add(
                f"{path}.effect",
                f"supported taint effect values: {', '.join(TAINT_EFFECTS)}",
            )
    errs.raise_if_any()


# ------------------------------------------------------------- the chain
_VALIDATORS = {
    "workloads": validate_workload,
    "clusterqueues": validate_cluster_queue,
    "localqueues": validate_local_queue,
    "cohorts": validate_cohort,
    "resourceflavors": validate_resource_flavor,
}

_DEFAULTERS = {
    "workloads": default_workload,
    "clusterqueues": default_cluster_queue,
    # cohorts carry the same resourceGroups shape (quantity canon)
    "cohorts": default_cluster_queue,
}


def default_admission_chain() -> List[Callable]:
    """The per-kind defaulter + validator stages the server installs
    (pkg/webhooks/webhooks.go:25 Setup analog)."""

    def _defaulting(section, obj, old, runtime):
        fn = _DEFAULTERS.get(section)
        return fn(obj, runtime) if fn else obj

    def _validating(section, obj, old, runtime):
        fn = _VALIDATORS.get(section)
        if fn:
            fn(obj, old)
        return obj

    return [_defaulting, _validating]
