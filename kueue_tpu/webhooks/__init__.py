"""Admission webhooks — defaulting + validation + immutability.

Reference: pkg/webhooks/{workload,clusterqueue,cohort,resourceflavor}
_webhook.go plus the CEL markers compiled into the CRDs
(apis/kueue/v1beta1/workload_types.go:637-641,
clusterqueue_types.go:49, localqueue_types.go:28). In the reference
these run inside the API server's admission phase; here they run at
ClusterRuntime ingress — the server applies the chain to every object
POSTed to /apis/kueue/v1beta1/*, and embedders can call
``default_admission_chain()`` themselves before feeding a runtime.

Each entry in the chain is ``admit(section, obj, old, runtime) ->
obj`` operating on wire-format dicts (serialization.py), raising
``ValidationError`` on rejection. Defaulting mutates a copy; the
caller persists whatever the chain returns.
"""

from kueue_tpu.webhooks.validation import (
    ValidationError,
    default_admission_chain,
    default_cluster_queue,
    default_workload,
    validate_cluster_queue,
    validate_cohort,
    validate_local_queue,
    validate_resource_flavor,
    validate_workload,
)

__all__ = [
    "ValidationError",
    "default_admission_chain",
    "default_cluster_queue",
    "default_workload",
    "validate_cluster_queue",
    "validate_cohort",
    "validate_local_queue",
    "validate_resource_flavor",
    "validate_workload",
]
